"""Continuous-batching online serving engine (ROADMAP item 1).

The reference's serving story ends at ``predictors.ModelPredictor`` —
offline batch inference over a dataset.  This module composes the offline
decode pieces (``core/decode.py``: KV-cache ``decode_step``, the factored
sampling surface, eos stopping) into a LIVE inference server with
iteration-level (Orca-style) scheduling:

 - **Slot pool** — one batched KV cache (``init_cache(model, num_slots,
   max_len)``); each batch row is a *slot* holding one in-flight request at
   its own position.  The whole pool advances through ONE jitted per-row
   ``decode_step`` (per-slot positions + active mask), so requests of
   different lengths share one compiled decode batch.
 - **Admission queue with backpressure** — ``submit`` enqueues up to
   ``queue_capacity`` requests; beyond that it blocks (or raises
   ``QueueFull`` with ``block=False`` — the wire server turns that into a
   backpressure reply instead of buffering unboundedly).
 - **Prefill/decode interleave** — each engine iteration admits up to
   ``prefills_per_step`` queued requests into free slots (one batched
   prompt forward each, scattered into the slot's cache row), then runs one
   decode step for every running request.  New work never stalls the
   running batch for more than a bounded number of prefills.
 - **Retirement + slot reuse** — a request leaves its slot the moment it
   emits ``eos_id`` or its ``num_steps``-th token; the slot is immediately
   reusable by the next queued request *mid-run* (continuous batching —
   the point of the whole engine).
 - **Hot weight reload** (stretch, off by default) — ``attach_ps`` points
   the engine at a live parameter server; between decode steps it pulls a
   fresh center over the existing ``'p'`` opcode, so training and serving
   can share one deployment.

Determinism contract: a lone request through the engine emits tokens
BIT-IDENTICAL to offline ``generate`` under the same seed/params
(tests/test_serving.py) — prefill runs the same eager ``_forward``,
decode sampling runs the factored ``sample_logits_batched`` whose per-row
math reproduces ``generate``'s ``sample_logits`` row for row.

The wire layer (``ServingServer``/``ServingClient``) speaks the same frame
codec + ``BufferPool`` transport as the PS stack, with two opcodes of its
own: ``'q'`` (enqueue request → ack/backpressure) and ``'r'`` (stream
reply chunks until done).  The serving protocol owns its port and its
opcode namespace — the PS protocol's ``'q'`` (quit) lives elsewhere.
"""

from __future__ import annotations

import collections
import logging
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import networking
from .core.decode import (_check_supported, _context_limit, _forward,
                          _to_ring, _validate_rolling, _validate_sampling,
                          _validate_stopping, _vocab_size, decode_step,
                          init_cache, sample_logits, sample_logits_batched)
from .core.model import FittedModel, Sequential

logger = logging.getLogger("distkeras_tpu.serving")

tmap = jax.tree_util.tree_map


class QueueFull(RuntimeError):
    """Admission backpressure: the engine's bounded queue is at capacity
    (``submit(block=False)`` / a blocking submit that timed out).  The wire
    server maps this to an ``{"ok": False, "error": "queue full"}`` reply —
    the client sheds or retries; the server never buffers unboundedly."""


class RequestHandle:
    """One submitted request's lifecycle + streaming surface.

    Produced tokens arrive incrementally (``next_chunk``) as the engine
    emits them; ``result()`` blocks until retirement and returns the full
    ``generate``-shaped row: prompt + emitted tokens, padded with
    ``pad_id`` (default ``eos_id``, else 0) out to ``num_steps`` — exactly
    the static-shape row offline ``generate`` would return.
    """

    __slots__ = ("id", "prompt", "num_steps", "temperature", "top_k",
                 "top_p", "eos_id", "pad_id", "key", "tokens", "finish",
                 "slot", "submitted_at", "started_at", "finished_at",
                 "_cond", "_chunk_read")

    def __init__(self, rid: int, prompt: np.ndarray, num_steps: int,
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float], eos_id: Optional[int],
                 pad_id: Optional[int], key):
        self.id = rid
        self.prompt = prompt
        self.num_steps = int(num_steps)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.key = key
        self.tokens: List[int] = []     # emitted (pre-padding) tokens
        self.finish: Optional[str] = None   # "eos" | "length" | "empty"
        self.slot: Optional[int] = None
        self.submitted_at = time.perf_counter()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._cond = threading.Condition()
        self._chunk_read = 0            # tokens already handed out as chunks

    @property
    def done(self) -> bool:
        return self.finish is not None

    @property
    def pad(self) -> int:
        return int(self.pad_id if self.pad_id is not None
                   else (self.eos_id or 0))

    # -- engine side ---------------------------------------------------------
    def _push(self, token: int) -> None:
        with self._cond:
            self.tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, reason: str) -> None:
        with self._cond:
            self.finish = reason
            self.finished_at = time.perf_counter()
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------
    def next_chunk(self, timeout: Optional[float] = None
                   ) -> Tuple[np.ndarray, bool]:
        """Block until new tokens exist (or the request finished); return
        ``(new_tokens, done)``.  After ``done`` the chunk may be empty —
        the stream's final frame."""
        with self._cond:
            self._cond.wait_for(
                lambda: self.done or len(self.tokens) > self._chunk_read,
                timeout=timeout)
            chunk = np.asarray(self.tokens[self._chunk_read:], np.int32)
            self._chunk_read = len(self.tokens)
            return chunk, self.done

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: self.done, timeout=timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full ``generate``-shaped row (prompt + tokens, padded to
        ``num_steps``) — blocks until the request retires."""
        if not self.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        gen = list(self.tokens) + [self.pad] * (self.num_steps
                                                - len(self.tokens))
        return np.concatenate([self.prompt,
                               np.asarray(gen, np.int32)])

    @property
    def latency_s(self) -> Optional[float]:
        return (None if self.finished_at is None
                else self.finished_at - self.submitted_at)


class ServingEngine:
    """Iteration-level continuous-batching engine over a slot-pooled KV
    cache.

    ``model``: a ``FittedModel`` (or ``(Sequential, params)`` pair) from the
    decode-supported family (``transformer_lm``).  ``num_slots`` is the
    decode batch — the number of simultaneously running requests;
    ``max_len`` bounds prompt+continuation per request (defaults to the
    model's positional range).  ``rolling=True`` (sliding-window models
    only) makes each slot an O(W) ring instead of ``max_len`` slots.

    Threading: ``submit`` is thread-safe (any number of producers);
    the scheduler itself — ``step`` / ``run_until_idle`` / the ``start``
    background thread — must be driven from ONE thread at a time.
    """

    def __init__(self, model: Union[FittedModel, Tuple[Sequential, Any]],
                 num_slots: int = 4, max_len: Optional[int] = None,
                 queue_capacity: int = 64, prefills_per_step: int = 1,
                 rolling: bool = False):
        if isinstance(model, FittedModel):
            self.model, self.params = model.model, model.params
        else:
            self.model, self.params = model
        _check_supported(self.model)
        if rolling:
            _validate_rolling(self.model)
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        limit = _context_limit(self.model)
        if max_len is None:
            if limit is None:
                raise ValueError("max_len is required for models without a "
                                 "positional-embedding range")
            max_len = limit
        if limit is not None and max_len > limit:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"positional-embedding range {limit}")
        self.max_len = int(max_len)
        self.rolling = bool(rolling)
        self.queue_capacity = int(queue_capacity)
        self.prefills_per_step = max(int(prefills_per_step), 1)
        self._vocab = _vocab_size(self.model)

        # -- slot pool: ONE batched cache, one host-side row of state per slot
        self.caches = init_cache(self.model, self.num_slots, self.max_len,
                                 rolling=self.rolling)
        self._handles: List[Optional[RequestHandle]] = [None] * self.num_slots
        self._free: List[int] = list(range(self.num_slots - 1, -1, -1))
        self._positions = np.zeros((self.num_slots,), np.int32)
        self._cur_tok = np.zeros((self.num_slots,), np.int32)
        self._active = np.zeros((self.num_slots,), bool)
        self._temp = np.zeros((self.num_slots,), np.float32)
        self._topk = np.zeros((self.num_slots,), np.int32)    # 0 = off
        self._topp = np.zeros((self.num_slots,), np.float32)  # 0 = off
        self._keys = np.zeros((self.num_slots, 2), np.uint32)

        # -- admission queue (the ONLY cross-thread state besides handles)
        self._queue: "collections.deque[RequestHandle]" = collections.deque()
        self._qlock = threading.Lock()
        self._not_full = threading.Condition(self._qlock)
        self._have_work = threading.Condition(self._qlock)
        self._next_id = 0

        # -- jitted programs (compiled once per engine: shapes are fixed)
        self._step_fn = self._build_step_fn()
        self._write_slot_fn = jax.jit(
            lambda big, row, s: tmap(
                lambda B, r: jax.lax.dynamic_update_slice(
                    B, r, (s, 0, 0, 0)), big, row),
            donate_argnums=(0,))

        # -- hot weight reload (stretch; off unless attach_ps is called)
        self._ps_addr: Optional[Tuple[str, int]] = None
        self._reload_every = 0
        self._reload_sock: Optional[socket.socket] = None
        self._reload_pool = networking.BufferPool()

        # -- scheduler thread + stats
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self.stats: Dict[str, Any] = {
            "requests_submitted": 0, "requests_completed": 0,
            "requests_rejected": 0, "tokens_generated": 0,
            "prefills": 0, "decode_steps": 0, "active_slot_steps": 0,
            "queue_peak": 0, "slot_requests": [0] * self.num_slots,
            "weight_reloads": 0,
        }

    # ------------------------------------------------------------------ jit
    def _build_step_fn(self):
        model, rolling = self.model, self.rolling

        def step(params, caches, tok, positions, active, temp, topk, topp,
                 keys):
            logits, caches = decode_step(model, params, caches, tok,
                                         positions, rolling)
            nxt = sample_logits_batched(logits, positions, temp, keys,
                                        topk, topp)
            # active mask: free slots keep their token (their row computes a
            # junk forward into their own cache row, which the next
            # prefill fully overwrites — never into anyone else's)
            return jnp.where(active, nxt, tok), caches

        return jax.jit(step, donate_argnums=(1,))

    # ------------------------------------------------------------ admission
    def submit(self, prompt, num_steps: int, temperature: float = 0.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               eos_id: Optional[int] = None, pad_id: Optional[int] = None,
               seed: int = 0, rng: Optional[jax.Array] = None,
               block: bool = True,
               timeout: Optional[float] = None) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`.

        ``prompt``: (P,) int tokens.  Sampling/stopping knobs mirror
        ``generate`` exactly (that is the bit-identity contract); the
        request's rng is ``rng`` if given, else ``PRNGKey(seed)``.
        Backpressure: with the queue at ``queue_capacity``, ``block=True``
        waits (up to ``timeout``), ``block=False`` raises :class:`QueueFull`
        immediately.
        """
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D tokens, got shape "
                             f"{prompt.shape} — submit one request per row")
        if num_steps < 0:
            raise ValueError(f"num_steps must be >= 0, got {num_steps}")
        key = rng if rng is not None else jax.random.PRNGKey(int(seed))
        _validate_sampling(temperature, key, top_k, top_p)
        _validate_stopping(eos_id, pad_id, self._vocab)
        total = len(prompt) + int(num_steps)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if total > self.max_len:
            raise ValueError(f"prompt ({len(prompt)}) + num_steps "
                             f"({num_steps}) = {total} exceeds the engine's "
                             f"max_len {self.max_len}")
        with self._qlock:
            self._next_id += 1
            handle = RequestHandle(self._next_id, prompt, num_steps,
                                   temperature, top_k, top_p, eos_id,
                                   pad_id, key)
            self.stats["requests_submitted"] += 1
            if num_steps == 0:  # nothing to generate: complete in place
                handle._finish("empty")
                self.stats["requests_completed"] += 1
                return handle
            while len(self._queue) >= self.queue_capacity:
                if not block or not self._not_full.wait(timeout=timeout):
                    self.stats["requests_rejected"] += 1
                    raise QueueFull(
                        f"admission queue at capacity "
                        f"({self.queue_capacity}); request {handle.id} shed")
            self._queue.append(handle)
            self.stats["queue_peak"] = max(self.stats["queue_peak"],
                                           len(self._queue))
            self._have_work.notify()
        return handle

    @property
    def queue_depth(self) -> int:
        with self._qlock:
            return len(self._queue)

    @property
    def active_requests(self) -> int:
        return int(self._active.sum())

    def _pop_queued(self) -> Optional[RequestHandle]:
        with self._qlock:
            if not self._queue:
                return None
            h = self._queue.popleft()
            self._not_full.notify()
            return h

    # ------------------------------------------------------------- prefill
    def _prefill(self, slot: int, h: RequestHandle) -> None:
        """Admit ``h`` into ``slot``: one batched prompt forward (the same
        eager ``_forward`` offline ``generate`` prefills with — identical
        numerics), first token sampled at ``p_len - 1`` through the shared
        ``sample_logits``, cache row scattered into the pool."""
        p_len = len(h.prompt)
        prompt = jnp.asarray(h.prompt[None], jnp.int32)
        row = init_cache(self.model, 1,
                         p_len if self.rolling else self.max_len)
        logits, row = _forward(self.model, self.params, row, prompt, 0)
        first = sample_logits(logits[:, -1], p_len - 1, h.temperature,
                              h.key, h.top_k, h.top_p)
        if self.rolling:
            ringed = []
            for layer, cache in zip(self.model.layers, row):
                if cache is None:
                    ringed.append(None)
                    continue
                w = layer._mha().attention_window
                ringed.append({name: _to_ring(cache[name], p_len, w)
                               for name in ("k", "v")})
            row = ringed
        self.caches = self._write_slot_fn(self.caches, row,
                                          jnp.int32(slot))
        h.slot = slot
        h.started_at = time.perf_counter()
        self._handles[slot] = h
        self._positions[slot] = p_len
        self._cur_tok[slot] = int(first[0])
        self._active[slot] = True
        self._temp[slot] = h.temperature
        self._topk[slot] = 0 if h.top_k is None else int(h.top_k)
        self._topp[slot] = 0.0 if h.top_p is None else float(h.top_p)
        self._keys[slot] = np.asarray(h.key, np.uint32)
        self.stats["prefills"] += 1
        self.stats["slot_requests"][slot] += 1
        self._emit(slot, int(first[0]))

    # ---------------------------------------------------------- retirement
    def _emit(self, slot: int, token: int) -> None:
        """Record one produced token for the request in ``slot``; retire on
        eos (the eos itself is emitted, as in ``generate``) or length."""
        h = self._handles[slot]
        h._push(token)
        self.stats["tokens_generated"] += 1
        if h.eos_id is not None and token == h.eos_id:
            self._retire(slot, "eos")
        elif len(h.tokens) >= h.num_steps:
            self._retire(slot, "length")

    def _retire(self, slot: int, reason: str) -> None:
        h = self._handles[slot]
        self._handles[slot] = None
        self._active[slot] = False
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 0.0
        self._positions[slot] = 0
        self._cur_tok[slot] = 0
        self._free.append(slot)
        self.stats["requests_completed"] += 1
        h._finish(reason)

    # ------------------------------------------------------------ schedule
    def step(self) -> bool:
        """One engine iteration: admit up to ``prefills_per_step`` queued
        requests into free slots (prefill), then advance every running
        request by one token (one batched per-row decode step).  Returns
        whether any work happened."""
        did = False
        for _ in range(self.prefills_per_step):
            if not self._free:
                break
            h = self._pop_queued()
            if h is None:
                break
            self._prefill(self._free.pop(), h)
            did = True
        if self._active.any():
            self._decode_once()
            did = True
        if did and self._reload_every:
            if self.stats["decode_steps"] % self._reload_every == 0:
                self._pull_weights()
        return did

    def _decode_once(self) -> None:
        nxt, self.caches = self._step_fn(
            self.params, self.caches, jnp.asarray(self._cur_tok),
            jnp.asarray(self._positions), jnp.asarray(self._active),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jnp.asarray(self._keys))
        nxt = np.asarray(nxt)
        self.stats["decode_steps"] += 1
        self.stats["active_slot_steps"] += int(self._active.sum())
        for slot in np.flatnonzero(self._active):
            self._positions[slot] += 1
            self._cur_tok[slot] = nxt[slot]
            self._emit(int(slot), int(nxt[slot]))

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        """Drive the scheduler inline until queue and slots are empty (the
        synchronous mode tests and closed-loop benches use)."""
        steps = 0
        while self.step():
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine still busy after {max_steps} steps "
                    f"(queue={self.queue_depth}, "
                    f"active={self.active_requests})")

    @property
    def slot_occupancy(self) -> Optional[float]:
        """Mean fraction of slots doing useful work per decode step — the
        continuous-batching health metric (1.0 = every step fully packed)."""
        if not self.stats["decode_steps"]:
            return None
        return (self.stats["active_slot_steps"]
                / (self.stats["decode_steps"] * self.num_slots))

    # ------------------------------------------------------- thread driver
    def start(self) -> "ServingEngine":
        """Run the scheduler on a background thread (the wire server's
        mode); idles on the work condition when nothing is queued/active."""
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dkt-serving-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        with self._qlock:
            self._have_work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if self._reload_sock is not None:
            try:
                networking.send_opcode(self._reload_sock, b"q")
                self._reload_sock.close()
            except OSError:
                pass
            self._reload_sock = None

    def _loop(self) -> None:
        while self._running:
            if not self.step():
                with self._qlock:
                    self._have_work.wait_for(
                        lambda: bool(self._queue) or not self._running,
                        timeout=0.05)

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------- hot reload (stretch)
    def attach_ps(self, host: str, port: int, every: int = 1) -> None:
        """Hot weight reload: pull a fresh center from a live parameter
        server (the PS stack's ``'p'`` opcode — same wire the training
        workers speak) every ``every`` decode steps, so a training run and
        this engine share one deployment.  The pull happens BETWEEN decode
        steps — in-flight requests simply continue on the new weights (the
        KV cache keeps old-weight k/v until those positions roll out, the
        standard live-reload tradeoff)."""
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self._ps_addr = (host, int(port))
        self._reload_every = int(every)

    def _pull_weights(self) -> None:
        try:
            if self._reload_sock is None:
                self._reload_sock = networking.connect(*self._ps_addr)
            networking.send_opcode(self._reload_sock, b"p")
            msg = networking.recv_data(self._reload_sock,
                                       pool=self._reload_pool)
            self.params = self.model.set_weights(self.params,
                                                 msg["weights"])
            self.stats["weight_reloads"] += 1
        except (ConnectionError, OSError, ValueError) as e:
            logger.warning("serving hot-reload pull failed (%s); keeping "
                           "current weights", e)
            if self._reload_sock is not None:
                try:
                    self._reload_sock.close()
                except OSError:
                    pass
                self._reload_sock = None


# ---------------------------------------------------------------------------
# wire layer: the serving protocol over the shared frame codec
# ---------------------------------------------------------------------------

#: serving-protocol opcodes (this protocol's own namespace — a serving
#: server port never speaks the PS protocol): 'q' enqueue request (frame:
#: prompt + sampling params → ack/backpressure reply), 'r' stream reply
#: (frame: {"id"} → chunk frames until {"done": True}).
OP_ENQUEUE = networking.SERVING_OP_ENQUEUE
OP_STREAM = networking.SERVING_OP_STREAM


class ServingServer:
    """TCP front-end for a :class:`ServingEngine` — same accept-loop /
    frame-codec / BufferPool idiom as ``SocketParameterServer``, so serving
    clients speak the exact wire the PS stack already speaks.

    Per connection: ``'q'`` + request frame → ack ``{"ok": True, "id": n}``
    or backpressure ``{"ok": False, "error": "queue full"}`` (the bounded
    admission queue shed the request — nothing was buffered); ``'r'`` +
    ``{"id": n}`` → a stream of ``{"id", "tokens", "done"}`` chunk frames,
    the last one carrying ``done=True`` + ``finish`` + the final padded
    ``row``.  EOF closes the connection; the engine keeps running.
    """

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0):
        self.engine = engine
        self.host = host
        self.port = int(port)
        self._handles: Dict[int, RequestHandle] = {}
        self._hlock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._running = False

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def start(self) -> "ServingServer":
        self.engine.start()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dkt-serving-accept")
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._running = False
        if self._server is not None:
            try:  # wake the blocked accept()
                socket.create_connection((self.host, self.port),
                                         timeout=1.0).close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.engine.stop()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            if not self._running:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True, name="dkt-serving-conn").start()

    def _handle(self, conn: socket.socket) -> None:
        # per-connection pools: requests land in a reusable receive buffer,
        # replies re-serialize into a reusable send buffer.  The send pool
        # is per-connection (BufferPool is lock-protected, but a shared
        # pool would still let another connection's encode overwrite a
        # frame between encode and sendall).
        recv_pool = networking.BufferPool()
        send_pool = networking.BufferPool()
        try:
            while True:
                op = networking.recv_opcode(conn)
                if op == b"":
                    return
                if op == OP_ENQUEUE:
                    msg = networking.recv_data(conn, pool=recv_pool)
                    try:
                        h = self.engine.submit(
                            np.array(msg["prompt"], np.int32, copy=True),
                            int(msg["num_steps"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            top_k=msg.get("top_k"),
                            top_p=msg.get("top_p"),
                            eos_id=msg.get("eos_id"),
                            pad_id=msg.get("pad_id"),
                            seed=int(msg.get("seed", 0)),
                            block=False)
                    except QueueFull:
                        networking.send_data(
                            conn, {"ok": False, "error": "queue full"},
                            pool=send_pool)
                        continue
                    except ValueError as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e)},
                            pool=send_pool)
                        continue
                    with self._hlock:
                        self._handles[h.id] = h
                    networking.send_data(conn, {"ok": True, "id": h.id},
                                         pool=send_pool)
                elif op == OP_STREAM:
                    msg = networking.recv_data(conn, pool=recv_pool)
                    with self._hlock:
                        h = self._handles.get(int(msg["id"]))
                    if h is None:
                        networking.send_data(
                            conn, {"ok": False, "done": True,
                                   "error": f"unknown id {msg['id']}"},
                            pool=send_pool)
                        continue
                    while True:
                        chunk, done = h.next_chunk(timeout=60.0)
                        reply = {"id": h.id, "tokens": chunk, "done": done}
                        if done:
                            reply["finish"] = h.finish
                            reply["row"] = h.result()
                        networking.send_data(conn, reply, pool=send_pool)
                        if done:
                            with self._hlock:
                                self._handles.pop(h.id, None)
                            break
                else:
                    return  # protocol violation: drop the connection
        except (ConnectionError, OSError, ValueError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)


class ServingClient:
    """Minimal client for :class:`ServingServer` — one socket, the shared
    frame codec, pooled receives.  ``generate`` is the one-call form whose
    returned row matches offline ``generate`` for the same request."""

    def __init__(self, host: str, port: int):
        self.sock = networking.connect(host, int(port))
        self._pool = networking.BufferPool()
        self._send_pool = networking.BufferPool()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def submit(self, prompt, num_steps: int, **kw) -> int:
        """Enqueue a request; returns the server-assigned id.  Raises
        :class:`QueueFull` on a backpressure reply."""
        req = {"prompt": np.asarray(prompt, np.int32),
               "num_steps": int(num_steps), **kw}
        networking.send_opcode(self.sock, OP_ENQUEUE)
        networking.send_data(self.sock, req, pool=self._send_pool)
        ack = networking.recv_data(self.sock, pool=self._pool)
        if not ack.get("ok"):
            err = ack.get("error", "rejected")
            if "queue full" in str(err):
                raise QueueFull(err)
            raise ValueError(err)
        return int(ack["id"])

    def stream(self, rid: int):
        """Yield ``(tokens, done_reply)`` chunk by chunk; ``done_reply`` is
        None until the final frame."""
        networking.send_opcode(self.sock, OP_STREAM)
        networking.send_data(self.sock, {"id": int(rid)},
                             pool=self._send_pool)
        while True:
            reply = networking.recv_data(self.sock, pool=self._pool)
            if reply.get("error"):
                raise ValueError(reply["error"])
            tokens = np.array(reply["tokens"], np.int32, copy=True)
            if reply["done"]:
                yield tokens, {"finish": reply["finish"],
                               "row": np.array(reply["row"], np.int32,
                                               copy=True)}
                return
            yield tokens, None

    def generate(self, prompt, num_steps: int, **kw) -> np.ndarray:
        """Submit + stream to completion; returns the full padded row
        (prompt + tokens), exactly ``generate``-shaped."""
        rid = self.submit(prompt, num_steps, **kw)
        for _, done in self.stream(rid):
            if done is not None:
                return done["row"]
        raise ConnectionError("stream ended without a done frame")
