"""Continuous-batching online serving engine (ROADMAP item 1).

The reference's serving story ends at ``predictors.ModelPredictor`` —
offline batch inference over a dataset.  This module composes the offline
decode pieces (``core/decode.py``: KV-cache ``decode_step``, the factored
sampling surface, eos stopping) into a LIVE inference server with
iteration-level (Orca-style) scheduling:

 - **Slot pool** — one batched KV cache (``init_cache(model, num_slots,
   max_len)``); each batch row is a *slot* holding one in-flight request at
   its own position.  The whole pool advances through ONE jitted per-row
   ``decode_step`` (per-slot positions + active mask), so requests of
   different lengths share one compiled decode batch.
 - **Admission queue with backpressure** — ``submit`` enqueues up to
   ``queue_capacity`` requests; beyond that it blocks (or raises
   ``QueueFull`` with ``block=False`` — the wire server turns that into a
   backpressure reply instead of buffering unboundedly).
 - **Prefill/decode interleave** — each engine iteration admits up to
   ``prefills_per_step`` queued requests into free slots, then runs one
   decode step for every running request.  New work never stalls the
   running batch for more than a bounded number of prefill work units.
 - **Compiled bucketed prefill** (``prefill_mode="bucketed"``, the
   default) — admitted prompts are right-padded to a small power-of-two
   length-bucket ladder and prefilled TOGETHER, one jitted batched forward
   per bucket (jit cache keyed on the bucket length; per-row
   ``kv_length`` masking keeps pad tokens out of every softmax), replacing
   the per-request eager ``_forward`` of the original engine — which is
   retained, bit-identical, behind ``prefill_mode="eager"`` as the
   reference path.
 - **Chunked prefill** — a prompt longer than ``prefill_chunk`` splits
   into chunks advanced one per scheduler iteration, interleaved with
   decode steps (Sarathi-style stall-free prefill): a 1024-token prompt
   no longer freezes every running request for its full length.  The slot
   sits in a *prefilling* state until its final chunk samples the first
   token.
 - **Device-resident decode state** — current tokens, positions, active
   mask, and per-slot sampling params live on device and are advanced
   INSIDE the jitted decode step; only the sampled token row is read back
   each iteration, and step t+1 is dispatched before the host finishes
   emitting step t's tokens (one-step lookahead, the serving twin of the
   host-PS ``comm_overlap`` idiom).
 - **Retirement + slot reuse** — a request leaves its slot the moment it
   emits ``eos_id`` or its ``num_steps``-th token; the slot is immediately
   reusable by the next queued request *mid-run* (continuous batching —
   the point of the whole engine).
 - **Batched per-slot speculative decoding** (``spec_draft=``, off by
   default) — a draft model rides the same slot layout in its OWN KV
   pool; each scheduler iteration drafts ``spec_len`` tokens per active
   row, verifies them all in ONE batched target forward, and commits
   heterogeneous per-row accept lengths (rows advance 1..spec_len+1
   positions per round) — all inside one jitted program, so a round
   costs one dispatch and one d2h like a plain step.  Greedy speculation
   is token-identical to non-speculative greedy.
 - **Quantization** (``quantize=``, ``kv_dtype=``, off by default) —
   int8/bf16 weight-only quantization applied at construction and on
   every hot-reload pull, and an int8 KV slot pool (codes + per-entry
   scales, dequantized inside the attention read) at roughly half the
   bf16 slot bytes — the ``num_slots``-doubling lever at fixed HBM.
 - **Hot weight reload** (stretch, off by default) — ``attach_ps`` points
   the engine at a live parameter server; between decode steps it pulls a
   fresh center over the existing ``'p'`` opcode, so training and serving
   can share one deployment.
 - **Failure semantics** (the serving twin of the host-PS robustness
   stack — see docs/serving.md's failure matrix): per-request
   **deadlines** (``submit(deadline_s=)`` / an engine-wide default) retire
   expired requests mid-run with reason ``"deadline"`` — queued ones are
   shed before ever taking a slot; **cancellation** (``engine.cancel``,
   the wire ``SERVING_OP_CANCEL`` opcode, and server-side
   client-disconnect detection) reclaims a KV slot within one scheduler
   iteration with reason ``"cancel"``; **graceful drain**
   (``engine.drain``) stops admission (``submit`` raises
   :class:`Draining`), finishes in-flight work, then stops; and a
   **crashed or wedged decode loop** fails every in-flight handle with a
   typed :class:`EngineDead` instead of hanging ``result()`` forever
   (``resilience.EngineSupervisor`` watches the loop's heartbeat and can
   restart the engine from the model weights with a fresh slot pool).

Determinism contract: a lone request through the engine emits tokens
BIT-IDENTICAL to offline ``generate`` under the same seed/params
(tests/test_serving.py) — prefill runs the same eager ``_forward``,
decode sampling runs the factored ``sample_logits_batched`` whose per-row
math reproduces ``generate``'s ``sample_logits`` row for row.

The wire layer (``ServingServer``/``ServingClient``) speaks the same frame
codec + ``BufferPool`` transport as the PS stack, with two opcodes of its
own: ``'q'`` (enqueue request → ack/backpressure) and ``'r'`` (stream
reply chunks until done).  The serving protocol owns its port and its
opcode namespace — the PS protocol's ``'q'`` (quit) lives elsewhere.
"""

from __future__ import annotations

import collections
import logging
import select
import selectors
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import networking
from .core import decode as _dec
from .core import quant as _quant
from .core.decode import (_check_supported, _context_limit, _forward,
                          _to_ring, _validate_rolling, _validate_sampling,
                          _validate_stopping, _vocab_size, decode_step,
                          init_cache, sample_logits, sample_logits_batched)
from .core.model import FittedModel, Sequential

logger = logging.getLogger("distkeras_tpu.serving")

from .resilience import RetryPolicy as _RetryPolicy  # noqa: E402 (no cycle:
# resilience imports networking only — and serving needs the policy type at
# module scope for the reload default below)

#: re-dial budget for ``attach_ps`` hot-reload pulls.  Deliberately TIGHT:
#: the pull runs on the decode thread between steps, so the policy's worst
#: case (attempts x backoff, deadline-capped) is the longest serving stall
#: a dead PS can cause — long enough to ride out a ``ShardSupervisor``
#: same-address respawn, short enough that serving p99 survives a PS that
#: is simply gone.  Override per-engine via ``attach_ps(retry_policy=...)``.
DEFAULT_RELOAD_POLICY = _RetryPolicy(attempts=4, backoff=0.02,
                                     max_backoff=0.1, jitter=0.0,
                                     deadline=0.5)

tmap = jax.tree_util.tree_map


class QueueFull(RuntimeError):
    """Admission backpressure: the engine's bounded queue is at capacity
    (``submit(block=False)`` / a blocking submit that timed out).  The wire
    server maps this to an ``{"ok": False, "error": "queue full"}`` reply —
    the client sheds or retries; the server never buffers unboundedly."""


class Draining(RuntimeError):
    """Admission refused because the engine is draining (``engine.drain``):
    in-flight requests finish, new ones go elsewhere.  The wire server maps
    this to a typed ``{"ok": False, "kind": "draining"}`` reply."""


class EngineDead(RuntimeError):
    """The serving engine's decode loop crashed, wedged, or was torn down
    with work in flight.  Raised from ``RequestHandle.result()`` for every
    request the dead engine was carrying (no silent hangs), and from
    ``submit`` on a dead engine.  The wire server maps it to a typed
    ``{"kind": "engine_dead"}`` frame; ``ServingClient.generate`` with a
    ``retry_policy`` treats it as retriable (requests are deterministic in
    their seed, so a resubmit is idempotent)."""


class QuotaExceeded(QueueFull):
    """Admission refused by the submitting tenant's token-bucket quota
    (``TenantPolicy.rate``).  Subclasses :class:`QueueFull` so every
    existing shed path (router spill, open-loop load shedding, wire
    backpressure) treats a quota refusal as sheddable — but the wire
    server replies with its own ``{"kind": "quota"}`` so clients can
    distinguish policy refusal from transient queue pressure.  Raised
    immediately even from a blocking ``submit``: waiting out a refill
    inside the engine would hold admission slots hostage to one tenant's
    burst."""


class TenantPolicy:
    """One tenant's QoS contract: ``weight`` is its weighted-fair share
    of admissions, ``rate``/``burst`` a token-bucket quota in requests/s
    (``rate=None`` = unlimited), ``tier`` the SLO band (``"interactive"``
    tenants are admitted ahead of ``"batch"`` tenants and may preempt
    them; ``"batch"`` tenants are preemptible), and ``deadline_s`` an
    optional tier-default per-request deadline applied when ``submit``
    passes none (explicit ``deadline_s`` still wins).  Bucket state is
    mutated under the engine's queue lock — one policy object belongs to
    one engine (``clone()`` for a fresh-bucket copy)."""

    __slots__ = ("name", "weight", "rate", "burst", "tier", "deadline_s",
                 "_tokens", "_stamp")

    def __init__(self, name: str, weight: float = 1.0,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None, tier: str = "batch",
                 deadline_s: Optional[float] = None):
        if not name:
            raise ValueError("tenant name must be non-empty")
        if not (weight > 0):
            raise ValueError(f"weight must be > 0, got {weight}")
        if rate is not None and not (rate > 0):
            raise ValueError(f"rate must be None or > 0, got {rate}")
        if tier not in ("interactive", "batch"):
            raise ValueError(f"tier must be 'interactive' or 'batch', "
                             f"got {tier!r}")
        if deadline_s is not None and not (deadline_s > 0):
            raise ValueError(f"deadline_s must be None or > 0, "
                             f"got {deadline_s}")
        self.name = str(name)
        self.weight = float(weight)
        self.rate = None if rate is None else float(rate)
        if burst is None:
            burst = None if rate is None else max(1.0, float(rate))
        elif not (burst >= 1.0):
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.burst = None if burst is None else float(burst)
        self.tier = tier
        self.deadline_s = deadline_s
        self._tokens = 0.0 if self.burst is None else self.burst
        self._stamp: Optional[float] = None

    def _take(self, now: float) -> bool:
        """Spend one bucket token (refilling first); False = over quota.
        Caller holds the engine's queue lock."""
        if self.rate is None:
            return True
        if self._stamp is not None:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._stamp)
                               * self.rate)
        self._stamp = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def clone(self) -> "TenantPolicy":
        """A copy with a full, unshared token bucket (the
        ``respawn_clone`` seam — the replacement engine must not inherit
        the dead engine's bucket debt)."""
        return TenantPolicy(self.name, weight=self.weight, rate=self.rate,
                            burst=self.burst, tier=self.tier,
                            deadline_s=self.deadline_s)


class RequestHandle:
    """One submitted request's lifecycle + streaming surface.

    Produced tokens arrive incrementally (``next_chunk``) as the engine
    emits them; ``result()`` blocks until retirement and returns the full
    ``generate``-shaped row: prompt + emitted tokens, padded with
    ``pad_id`` (default ``eos_id``, else 0) out to ``num_steps`` — exactly
    the static-shape row offline ``generate`` would return.

    ``finish`` is the retire reason: ``"eos"`` / ``"length"`` / ``"empty"``
    for normal completion, ``"deadline"`` (per-request deadline expired —
    the partial row is still returned, padded), ``"cancel"`` (explicit
    cancel or client disconnect), ``"drain"`` (drain timeout), ``"error"``
    (the engine died — ``result()`` raises the stored :class:`EngineDead`),
    ``"prefilled"`` (a ``role="prefill"`` engine finished its half: the
    first token is pushed and ``kvblocks`` holds the request's extracted
    KV blocks for the decode engine — disaggregated serving's hand-off).
    ``deadline`` is an absolute ``time.perf_counter()`` instant or None.
    """

    __slots__ = ("id", "prompt", "num_steps", "temperature", "top_k",
                 "top_p", "eos_id", "pad_id", "key", "tokens", "finish",
                 "slot", "submitted_at", "started_at", "first_token_at",
                 "finished_at", "deadline", "error", "cancelled_at",
                 "kvblocks", "tenant", "priority", "_cond", "_chunk_read",
                 "_listener")

    def __init__(self, rid: int, prompt: np.ndarray, num_steps: int,
                 temperature: float, top_k: Optional[int],
                 top_p: Optional[float], eos_id: Optional[int],
                 pad_id: Optional[int], key,
                 deadline_s: Optional[float] = None,
                 tenant: str = "default", priority: int = 0):
        self.id = rid
        self.prompt = prompt
        self.num_steps = int(num_steps)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.key = key
        self.tokens: List[int] = []     # emitted (pre-padding) tokens
        self.finish: Optional[str] = None   # see class docstring
        self.slot: Optional[int] = None
        self.submitted_at = time.perf_counter()
        self.started_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.deadline = (None if deadline_s is None
                         else self.submitted_at + float(deadline_s))
        self.error: Optional[BaseException] = None
        self.cancelled_at: Optional[float] = None
        #: networking.KVBlocks on a "prefilled" handle (prefill role's
        #: extraction output) or on a decode-role ingest before admission
        self.kvblocks = None
        self.tenant = str(tenant)
        self.priority = int(priority)
        self._cond = threading.Condition()
        self._chunk_read = 0            # tokens already handed out as chunks
        #: event-transport hook: a no-arg callable invoked (OUTSIDE
        #: ``_cond``) whenever tokens arrive or the handle retires — how
        #: the selector cores get poked without a polling thread per
        #: stream.  Set via ``set_listener``; polling consumers ignore it.
        self._listener: Optional[Callable[[], None]] = None

    @property
    def done(self) -> bool:
        return self.finish is not None

    @property
    def pad(self) -> int:
        return int(self.pad_id if self.pad_id is not None
                   else (self.eos_id or 0))

    # -- engine side ---------------------------------------------------------
    def _push(self, token: int) -> None:
        with self._cond:
            if self.finish is not None:  # a wedged loop emitting past its
                return                   # declared death: drop, don't grow
            if self.first_token_at is None:
                self.first_token_at = time.perf_counter()
            self.tokens.append(int(token))
            self._cond.notify_all()
            fire = self._listener
        if fire is not None:  # invoked OUTSIDE _cond: the listener hops
            fire()            # threads (call_soon) and must not nest locks

    def _finish(self, reason: str) -> bool:
        """Returns whether THIS call made the handle terminal — the engine
        only counts a request once, so a completion racing a concurrent
        failure (or vice versa) must not increment both counters."""
        with self._cond:
            if self.finish is not None:  # first terminal state wins (a
                return False             # wedge diagnosis is never undone)
            self.finish = reason
            self.finished_at = time.perf_counter()
            self._cond.notify_all()
            fire = self._listener
        if fire is not None:
            fire()
        return True

    def _fail(self, exc: BaseException, reason: str = "error") -> bool:
        """Terminal failure: ``result()`` raises ``exc`` instead of
        returning a row.  Idempotent like ``_finish``; same return
        contract."""
        with self._cond:
            if self.finish is not None:
                return False
            self.error = exc
            self.finish = reason
            self.finished_at = time.perf_counter()
            self._cond.notify_all()
            fire = self._listener
        if fire is not None:
            fire()
        return True

    def set_listener(self, fn: Optional[Callable[[], None]]) -> None:
        """Install (or clear, with None) the progress listener — fired
        after every token push and on retirement, outside the handle's
        lock.  One listener at a time; the event transports each attach
        their loop-poke here while they own the stream."""
        with self._cond:
            self._listener = fn
        if fn is not None and (self.done or len(self.tokens)):
            fn()  # catch up on progress that predates the listener

    def _expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    # -- consumer side -------------------------------------------------------
    def next_chunk(self, timeout: Optional[float] = None
                   ) -> Tuple[np.ndarray, bool]:
        """Block until new tokens exist (or the request finished); return
        ``(new_tokens, done)``.  After ``done`` the chunk may be empty —
        the stream's final frame."""
        with self._cond:
            self._cond.wait_for(
                lambda: self.done or len(self.tokens) > self._chunk_read,
                timeout=timeout)
            chunk = np.asarray(self.tokens[self._chunk_read:], np.int32)
            self._chunk_read = len(self.tokens)
            return chunk, self.done

    def wait(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            return self._cond.wait_for(lambda: self.done, timeout=timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full ``generate``-shaped row (prompt + tokens, padded to
        ``num_steps``) — blocks until the request retires.  A request the
        engine failed (crash / wedge / drain timeout) raises its stored
        typed error (:class:`EngineDead`) instead of hanging or returning
        a fabricated row."""
        if not self.wait(timeout):
            raise TimeoutError(f"request {self.id} not done")
        if self.error is not None:
            raise self.error
        gen = list(self.tokens) + [self.pad] * (self.num_steps
                                                - len(self.tokens))
        return np.concatenate([self.prompt,
                               np.asarray(gen, np.int32)])

    @property
    def latency_s(self) -> Optional[float]:
        return (None if self.finished_at is None
                else self.finished_at - self.submitted_at)

    @property
    def ttft_s(self) -> Optional[float]:
        """Time to first token — submit instant → first emitted token
        (queueing AND prefill included), the latency a streaming client
        actually feels.  None until the first token exists."""
        return (None if self.first_token_at is None
                else self.first_token_at - self.submitted_at)


def _quantize_weights(params, mode: str):
    """The engine's one weight-quantization path (construction AND every
    ``attach_ps`` hot-reload pull go through it): ``"int8"`` —
    ``quantize_params`` weight-only post-training quantization (matmul
    kernels become (codes, scale) leaves that dequantize inside the
    unmodified forward); ``"bf16"`` — every float leaf cast to bfloat16
    (half the f32 weight traffic, no code change).  Idempotent."""
    if mode == "int8":
        return _quant.quantize_params(params)
    # bf16: cast float leaves; QuantizedTensor leaves (already int8) and
    # integer leaves pass through untouched
    def cast(x):
        if isinstance(x, _quant.QuantizedTensor):
            return x
        if hasattr(x, "dtype") and jnp.issubdtype(
                jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x).astype(jnp.bfloat16)
        return x
    return tmap(cast, params,
                is_leaf=lambda x: isinstance(x, _quant.QuantizedTensor))


def _commit_rows(big, row, slots, width: int, rolling: bool, p_lens):
    """Scatter freshly-prefilled full-precision cache rows into the slot
    pool: ring-converted per row for rolling pools, quantize-on-commit for
    int8 pools (same per-entry scales the decode-time writes produce).
    ``slots`` rows carrying index ``num_slots`` drop every write."""
    if big is None:
        return None
    if rolling:
        w = big["k"].shape[1]
        row = {n: _dec.ring_from_prefill(row[n], p_lens, w)
               for n in ("k", "v")}

        def put(dst, src):
            return dst.at[slots].set(src, mode="drop")
    else:
        def put(dst, src):
            return dst.at[slots, :width].set(src, mode="drop")
    if "ks" in big:
        kq, ks = _quant.quantize_kv(row["k"])
        vq, vs = _quant.quantize_kv(row["v"])
        return {"k": put(big["k"], kq), "v": put(big["v"], vq),
                "ks": put(big["ks"], ks), "vs": put(big["vs"], vs)}
    return {n: put(big[n], row[n]) for n in ("k", "v")}


def _commit_full_row(big, row, slot, rolling: bool, p_row):
    """The chunked-prefill final commit: one staged full-length row
    atomically replaces pool row ``slot`` (ring-collapsed for rolling
    pools, quantize-on-commit for int8 pools)."""
    if big is None:
        return None
    if rolling:
        w = big["k"].shape[1]
        row = {n: _dec.ring_from_prefill(row[n], p_row, w)
               for n in ("k", "v")}
    if "ks" in big:
        kq, ks = _quant.quantize_kv(row["k"])
        vq, vs = _quant.quantize_kv(row["v"])
        return {"k": big["k"].at[slot].set(kq[0], mode="drop"),
                "v": big["v"].at[slot].set(vq[0], mode="drop"),
                "ks": big["ks"].at[slot].set(ks[0], mode="drop"),
                "vs": big["vs"].at[slot].set(vs[0], mode="drop")}
    return {n: big[n].at[slot].set(row[n][0], mode="drop")
            for n in ("k", "v")}


def _pow2_buckets(cap: int) -> List[int]:
    """The prefill length-bucket ladder: powers of two from 8 up, capped
    (and terminated) at ``cap`` — a SMALL set, so each bucket's jitted
    batched-prefill program compiles once and is reused for every prompt
    that rounds up to it."""
    cap = int(cap)
    out: List[int] = []
    n = 8
    while n < cap:
        out.append(n)
        n *= 2
    out.append(cap)
    return out


class _PrefillJob:
    """Scheduler-side state of one chunked prefill in flight: the slot is
    claimed (``engine._handles``) but not yet decoding; ``written`` prompt
    tokens are staged so far.  ``staging`` is a full-length one-row cache
    the chunks accumulate into — private to the job, so the decode
    batch's junk writes into free pool rows can't race it — which the
    final chunk commits to the slot's pool row in one atomic program
    (ring-collapsed for rolling engines).

    Paged engines (non-rolling) chunk IN-ARENA instead: the job's blocks
    are private by construction (every other row's writes go through its
    OWN block table, and the prefilling slot's device table stays null
    until the final chunk installs it — junk decode passes drop into the
    null block), so the dense path's staging race cannot exist and the
    chunks write straight into the request's allocated blocks (``bt`` /
    ``dbt`` hold the row's uploaded block tables)."""

    __slots__ = ("handle", "staging", "d_staging", "written", "bt", "dbt")

    def __init__(self, handle: RequestHandle, staging=None, d_staging=None,
                 bt=None, dbt=None):
        self.handle = handle
        self.staging = staging
        self.d_staging = d_staging  # the draft model's twin (speculation)
        self.bt = bt                # paged: (1, T) device block-table row
        self.dbt = dbt
        self.written = 0


class _SuspendedReq:
    """One preempted request swapped out to host memory: the live KV
    blocks (``layers`` — per-layer dicts of host arrays, ``n_blocks`` ×
    ``block_size`` rows each, the same layout ``networking.KVBlocks``
    ships) plus the decode frontier (``pos`` device positions written,
    ``tok`` the current un-written token).  The handle itself stays
    non-terminal — tokens already emitted remain on it, and the RNG key
    (``handle.key``) folds per absolute position, so re-installing
    (tok, pos, key) over the restored blocks resumes a bit-identical
    stream.  Holds NO slot and NO arena blocks."""

    __slots__ = ("handle", "layers", "n_blocks", "pos", "tok",
                 "suspended_at")

    def __init__(self, handle: RequestHandle, layers, n_blocks: int,
                 pos: int, tok: int):
        self.handle = handle
        self.layers = layers
        self.n_blocks = int(n_blocks)
        self.pos = int(pos)
        self.tok = int(tok)
        self.suspended_at = time.perf_counter()


# ---------------------------------------------------------------------------
# paged KV pool: host-side block allocator + radix prefix index
# ---------------------------------------------------------------------------

class _RadixNode:
    """One FULL block of prompt tokens in the prefix trie: ``key`` is its
    ``block_size``-token chunk (the edge label from ``parent``), ``block``
    the physical arena block holding those tokens' K/V (all layers, both
    pools — target and draft arenas share one block-id namespace).
    ``ref`` counts live requests currently sharing the block; at ref 0
    the node stays CACHED (its K/V remain valid in the arena) until the
    allocator evicts it — LRU over ``last_used``, leaves first, so a
    chain is reclaimed suffix-inward.  ``epoch`` stamps the scheduler
    pass that inserted it: a node is matchable only from LATER passes,
    which is what keeps a same-pass matcher from reading blocks whose
    prefill program (possibly a different bucket group) has not been
    dispatched yet."""

    __slots__ = ("parent", "key", "block", "ref", "last_used", "children",
                 "epoch")

    def __init__(self, parent, key: Tuple[int, ...], block: int,
                 epoch: int = -1):
        self.parent = parent
        self.key = key
        self.block = block
        self.ref = 0
        self.last_used = 0
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.epoch = epoch


class _BlockPlan:
    """One admitted request's block bookkeeping: ``blocks`` is the full
    logical chain (matched + fresh, in logical-block order), ``nodes``
    the trie nodes it holds a reference on, ``private`` the block ids it
    owns outright (COW copy, partial prompt boundary, decode region),
    ``matched`` the prefix tokens served from the trie, and ``cow`` the
    ``(src, dst)`` block pair of the copy-on-write boundary copy (or
    None)."""

    __slots__ = ("nodes", "private", "blocks", "matched", "cow")

    def __init__(self, nodes, private, blocks, matched, cow):
        self.nodes = nodes
        self.private = private
        self.blocks = blocks
        self.matched = matched
        self.cow = cow


class _PagedKVPool:
    """Host-side allocator + radix prefix index over a flat block arena
    (``core.decode.init_paged_arena``).  All scheduler-thread-only.

    Allocation is block-granular and on demand: a request takes
    ``ceil((p_len + num_steps) / block_size)`` blocks instead of a
    ``max_len`` row, so capacity is bounded by TOKENS IN FLIGHT rather
    than ``num_slots × max_len``.  With ``share=True`` (non-rolling
    pools) admissions first walk the trie: full blocks whose token chunk
    matches the prompt are SHARED (refcounted — never written again:
    every sharer's write floor sits above them), a partially-matched
    boundary block is COPIED (copy-on-write: the admission owns the
    copy and continues writing into it), and only the unmatched suffix
    is prefilled.  Matching is capped at ``p_len - 1`` so at least one
    prompt token is always prefilled — the logits that sample the first
    token must be computed.  Retirement decrements refs; refcount-0
    chains stay cached until LRU eviction (leaves first) reclaims their
    blocks for new admissions.  Stats are written straight into the
    engine's ``stats`` dict."""

    def __init__(self, num_blocks: int, block_size: int, share: bool,
                 stats: Dict[str, Any]):
        self.num_blocks = int(num_blocks)
        self.bs = int(block_size)
        self.share = bool(share)
        self.stats = stats
        self.free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self.root = _RadixNode(None, (), -1)
        self.private_out = 0
        self._clock = 0
        self.epoch = 0
        #: incremental mirror of ``cached_blocks()`` — kept so the
        #: engine's lock-free load snapshot reads an int instead of
        #: walking the trie (O(nodes)) on every publish
        self.trie_nodes = 0

    # -- clocks ------------------------------------------------------------
    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def next_epoch(self) -> None:
        """One scheduler pass = one epoch: nodes inserted this pass are
        not matchable until the next (their prefill program may belong
        to a bucket group dispatched AFTER the matcher's)."""
        self.epoch += 1

    # -- introspection -----------------------------------------------------
    def _nodes(self) -> List[_RadixNode]:
        out, stack = [], list(self.root.children.values())
        while stack:
            n = stack.pop()
            out.append(n)
            stack.extend(n.children.values())
        return out

    def cached_blocks(self) -> int:
        """Trie-held blocks (shared + refcount-0 cached)."""
        return len(self._nodes())

    def in_use(self) -> int:
        """Blocks held by LIVE requests: privately-owned ones plus trie
        nodes with a non-zero refcount.  0 when the engine is idle — the
        zero-leak assertion every retirement path must restore."""
        return self.private_out + sum(1 for n in self._nodes() if n.ref > 0)

    def check_conservation(self) -> bool:
        """free + cached == num_blocks − private_out, always."""
        return (len(self.free) + self.cached_blocks() + self.private_out
                == self.num_blocks)

    # -- match / evict / allocate ------------------------------------------
    def _match(self, toks: List[int], cap: int):
        """Walk the trie: full-block matches (chain), then the best
        PARTIAL child match at the divergence point (the COW boundary).
        ``cap`` bounds matchable tokens (< p_len, see class docstring).
        Nodes inserted this epoch are invisible."""
        nodes: List[_RadixNode] = []
        parent = self.root
        d = 0
        while d + self.bs <= cap:
            child = parent.children.get(tuple(toks[d:d + self.bs]))
            if child is None or child.epoch >= self.epoch:
                break
            nodes.append(child)
            parent = child
            d += self.bs
        pnode, plen = None, 0
        lim = min(cap - d, self.bs)
        if lim > 0:
            for key, child in parent.children.items():
                if child.epoch >= self.epoch:
                    continue
                j = 0
                while j < lim and key[j] == toks[d + j]:
                    j += 1
                if j > plen:
                    pnode, plen = child, j
        return nodes, pnode, plen

    def _evictable(self, pinned) -> List[_RadixNode]:
        out, stack = [], list(self.root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif n.ref == 0 and n not in pinned:
                out.append(n)
        return out

    def _reserve(self, need: int, pinned=()) -> bool:
        """Ensure ``need`` free blocks, evicting LRU refcount-0 leaf
        chains (suffix-inward); False when live requests hold too much —
        the admission stays queued until retirements free blocks."""
        pinned = set(pinned)
        while len(self.free) < need:
            cands = self._evictable(pinned)
            if not cands:
                return False
            victim = min(cands, key=lambda n: n.last_used)
            del victim.parent.children[victim.key]
            self.free.append(victim.block)
            self.trie_nodes -= 1
            self.stats["blocks_evicted"] += 1
        return True

    def admit(self, tokens, n_blocks: int) -> Optional[_BlockPlan]:
        """Reserve a request's block chain.  ``tokens`` (the prompt) is
        None for share-off (rolling) pools — a plain allocation.  Trie
        INSERTION of the request's own full prompt blocks is deferred to
        :meth:`publish` (after their contents' program is dispatched).
        Returns None when blocks are unavailable (admission backs off)."""
        if not self.share or tokens is None:
            if not self._reserve(n_blocks):
                return None
            fresh = [self.free.pop() for _ in range(n_blocks)]
            self.stats["blocks_allocated"] += n_blocks
            self.private_out += n_blocks
            return _BlockPlan([], fresh, list(fresh), 0, None)
        toks = [int(t) for t in tokens]
        cap = len(toks) - 1
        nodes, pnode, plen = self._match(toks, cap)
        matched = len(nodes) * self.bs + plen
        need = n_blocks - len(nodes)
        pinned = list(nodes) + ([pnode] if pnode is not None else [])
        if not self._reserve(need, pinned):
            return None
        fresh = [self.free.pop() for _ in range(need)]
        self.stats["blocks_allocated"] += need
        self.private_out += need
        chain = [n.block for n in nodes] + fresh
        now = self._tick()
        for n in nodes:
            n.ref += 1
            n.last_used = now
        self.stats["blocks_reused"] += len(nodes)
        if matched:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_hit_tokens"] += matched
        cow = None
        if pnode is not None:
            cow = (pnode.block, chain[len(nodes)])
            pnode.last_used = now
            self.stats["cow_copies"] += 1
        return _BlockPlan(list(nodes), fresh, chain, matched, cow)

    def publish(self, plan: _BlockPlan, tokens) -> None:
        """Insert the request's own FULL prompt blocks into the trie
        (ref 1 — held live until release) so later admissions can share
        them.  Called once the program writing their contents has been
        dispatched: immediately for bucket prefills, at the final chunk
        for chunked ones (earlier would let a matcher's program overtake
        an undispatched chunk).  Stops at the first key collision —
        a concurrent chain insertion keeps the existing nodes and this
        plan's duplicates stay private."""
        if not self.share or tokens is None:
            return
        toks = [int(t) for t in tokens]
        parent = plan.nodes[-1] if plan.nodes else self.root
        now = self._tick()
        i = len(plan.nodes)
        while (i + 1) * self.bs <= len(toks) and i < len(plan.blocks):
            key = tuple(toks[i * self.bs:(i + 1) * self.bs])
            if key in parent.children:
                break
            node = _RadixNode(parent, key, plan.blocks[i], self.epoch)
            node.ref = 1
            node.last_used = now
            parent.children[key] = node
            plan.nodes.append(node)
            plan.private.remove(plan.blocks[i])
            self.private_out -= 1
            self.trie_nodes += 1
            parent = node
            i += 1

    def release(self, plan: _BlockPlan) -> None:
        """Retirement: drop the plan's refs (refcount-0 chains stay
        cached for future prefix hits) and free its private blocks."""
        now = self._tick()
        for n in plan.nodes:
            n.ref -= 1
            n.last_used = now
        self.free.extend(plan.private)
        self.private_out -= len(plan.private)
        plan.nodes, plan.private = [], []


class ServingEngine:
    """Iteration-level continuous-batching engine over a slot-pooled KV
    cache.

    ``model``: a ``FittedModel`` (or ``(Sequential, params)`` pair) from the
    decode-supported family (``transformer_lm``).  ``num_slots`` is the
    decode batch — the number of simultaneously running requests;
    ``max_len`` bounds prompt+continuation per request (defaults to the
    model's positional range).  ``rolling=True`` (sliding-window models
    only) makes each slot an O(W) ring instead of ``max_len`` slots.

    ``prefill_mode``: ``"bucketed"`` (default) runs the compiled fast
    path — batched bucket prefill, chunked long-prompt prefill, and
    device-resident decode state with one-step lookahead; ``"eager"`` is
    the original per-request eager-``_forward`` engine, retained as the
    bit-identical reference.  ``prefill_chunk`` bounds how many prompt
    tokens one scheduler iteration may prefill for a single request
    (bucketed mode): longer prompts split into chunks interleaved with
    decode steps, so admissions never stall the running batch for more
    than one chunk per iteration.

    Speculation + quantization (all default OFF — defaults are
    bit-identical to the pre-speculation engine):

     - ``spec_draft`` (bucketed mode): a cheaper draft model
       (``FittedModel`` or ``(Sequential, params)``, same vocabulary)
       turns every decode iteration into a speculative ROUND — ``spec_len``
       per-slot draft steps against the draft's own slot-pooled KV cache,
       one batched target verify forward, heterogeneous per-row accept
       lengths (each row advances 1..spec_len+1 positions).  Greedy
       requests stay token-identical to non-speculative greedy (the
       committed chain is the target's own argmax chain); sampled
       requests follow the Leviathan/Chen rejection rule —
       distribution-exact, deterministic per seed, but a different (and
       documented) key-fold schedule than the non-speculative sampler.
     - ``quantize``: ``"int8"`` (weight-only post-training quantization
       through ``core.quant.quantize_params``) or ``"bf16"`` — applied at
       construction and re-applied to every ``attach_ps`` hot-reload
       pull.  Lossy; the eager engine stays the full-precision reference.
     - ``kv_dtype="int8"`` (bucketed mode): the slot pools (target and
       draft) store int8 codes + per-entry scales — roughly half the
       bf16 slot bytes, so ``num_slots`` can ~double at fixed pool HBM
       (``kv_pool_bytes`` is the byte-accounted observable).  Lossy.
     - ``paged=True`` (bucketed mode): the slot pool becomes a PAGED KV
       pool — a flat arena of ``kv_blocks`` fixed-size blocks
       (``block_size`` tokens each, int8 codes + scales paged identically
       when ``kv_dtype="int8"``) with per-request block tables, so a
       request allocates ``ceil((p_len + num_steps) / block_size)``
       blocks instead of a ``max_len`` row and capacity is bounded by
       tokens in flight.  On top of the arena a host-side RADIX PREFIX
       INDEX maps full prompt blocks to refcounted chains: an admission
       walks the trie, SHARES matched full blocks (copy-on-write at a
       partially-matched boundary block), and prefills only the
       unmatched suffix — TTFT for a shared-prefix admission drops from
       O(prompt) to O(suffix).  Refcount-0 chains stay cached until LRU
       eviction.  Speculative engines page the draft pool over the SAME
       block chain (one trie serves both).  Exact: a lone request's
       output is token-identical to the dense engine and to offline
       ``generate``; the default ``paged=False`` keeps the dense pool
       byte-for-byte.

    Threading: ``submit`` is thread-safe (any number of producers);
    the scheduler itself — ``step`` / ``run_until_idle`` / the ``start``
    background thread — must be driven from ONE thread at a time.
    """

    def __init__(self, model: Union[FittedModel, Tuple[Sequential, Any]],
                 num_slots: int = 4, max_len: Optional[int] = None,
                 queue_capacity: int = 64, prefills_per_step: int = 1,
                 rolling: bool = False,
                 default_deadline_s: Optional[float] = None,
                 prefill_mode: str = "bucketed", prefill_chunk: int = 128,
                 spec_draft: Optional[Union[FittedModel,
                                            Tuple[Sequential, Any]]] = None,
                 spec_len: int = 4,
                 quantize: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 paged: bool = False, block_size: int = 16,
                 kv_blocks: Optional[int] = None,
                 role: str = "unified",
                 tenants: Optional[List[TenantPolicy]] = None):
        if isinstance(model, FittedModel):
            self.model, self.params = model.model, model.params
        else:
            self.model, self.params = model
        _check_supported(self.model)
        if rolling:
            _validate_rolling(self.model)
        # -- disaggregation role (default "unified": the engine is exactly
        #    its pre-disaggregation self).  "prefill": admissions run the
        #    ordinary paged prefill programs but STOP before the token
        #    loop — the request retires "prefilled" with its KV blocks
        #    extracted onto the handle.  "decode": admission comes from
        #    submit_prefilled (a shipped block set scattered into this
        #    engine's own arena blocks); plain submit is rejected.
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role must be 'unified', 'prefill' or "
                             f"'decode', got {role!r}")
        if role != "unified":
            if not paged:
                raise ValueError(
                    f"role={role!r} needs the paged block arena "
                    "(paged=True): block transfer is defined over "
                    "fixed-size arena blocks")
            if rolling:
                raise ValueError(
                    f"role={role!r} does not compose with rolling pools — "
                    "ring-laid blocks are not positionally addressable on "
                    "the receiving side")
            if spec_draft is not None:
                raise ValueError(
                    f"role={role!r} does not compose with spec_draft: the "
                    "draft arena is engine-private and never shipped")
        self.role = role
        # -- speculation + quantization knobs (all default OFF: the engine
        #    is bit-identical to its pre-speculation self until asked)
        if prefill_mode == "eager" and (spec_draft is not None
                                        or kv_dtype is not None or paged):
            raise ValueError(
                "spec_draft / kv_dtype / paged are fast-path features "
                "(prefill_mode='bucketed'); the eager engine stays the "
                "unmodified bit-exactness reference")
        if quantize not in (None, "int8", "bf16"):
            raise ValueError(f"quantize must be None, 'int8' or 'bf16', "
                             f"got {quantize!r}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', got "
                             f"{kv_dtype!r}")
        if int(spec_len) < 1:
            raise ValueError(f"spec_len must be >= 1, got {spec_len}")
        self.spec_len = int(spec_len)
        self.quantize = quantize
        self.kv_dtype = kv_dtype
        if spec_draft is None:
            self._draft_model, self._draft_params = None, None
        else:
            if isinstance(spec_draft, FittedModel):
                self._draft_model = spec_draft.model
                self._draft_params = spec_draft.params
            else:
                self._draft_model, self._draft_params = spec_draft
            _check_supported(self._draft_model)
            tv, dv = _vocab_size(self.model), _vocab_size(self._draft_model)
            if tv is not None and dv is not None and tv != dv:
                raise ValueError(
                    f"target and draft vocabularies differ: {tv} vs {dv} — "
                    f"draft proposals would be meaningless")
        # quantize weights ONCE at construction; attach_ps re-quantizes
        # every pulled center through the same path.  The f32 skeleton
        # (scalar zeros of the pre-quant dtypes) is what set_weights maps
        # a pulled flat weight list onto before re-quantization
        if quantize is not None:
            self._fp_skel = tmap(lambda x: np.zeros((), np.asarray(x).dtype),
                                 self.params)
            self.params = _quantize_weights(self.params, quantize)
            if self._draft_params is not None:
                self._draft_params = _quantize_weights(self._draft_params,
                                                       quantize)
        else:
            self._fp_skel = None
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        limit = _context_limit(self.model)
        if max_len is None:
            if limit is None:
                raise ValueError("max_len is required for models without a "
                                 "positional-embedding range")
            max_len = limit
        if limit is not None and max_len > limit:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"positional-embedding range {limit}")
        self.max_len = int(max_len)
        self.rolling = bool(rolling)
        self.queue_capacity = int(queue_capacity)
        self.prefills_per_step = max(int(prefills_per_step), 1)
        if prefill_mode not in ("bucketed", "eager"):
            raise ValueError(f"prefill_mode must be 'bucketed' or 'eager', "
                             f"got {prefill_mode!r}")
        self.prefill_mode = prefill_mode
        if int(prefill_chunk) < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        self.prefill_chunk = int(prefill_chunk)
        if default_deadline_s is not None and default_deadline_s <= 0:
            raise ValueError(f"default_deadline_s must be > 0, got "
                             f"{default_deadline_s}")
        self.default_deadline_s = default_deadline_s
        self._vocab = _vocab_size(self.model)

        # -- slot pool: ONE batched cache, one host-side row of state per
        #    slot.  With speculation on a rolling pool the ring gets
        #    spec_len slots of slack so the L = spec_len + 1 verify write
        #    never overwrites the oldest query's attention window; with
        #    kv_dtype="int8" entries are stored as codes + per-entry
        #    scales at roughly half the bf16 slot bytes.  The draft model
        #    gets its OWN pool over the same slot indices (full-length:
        #    draft caches are small next to the target's)
        ring_slack = (self.spec_len if (rolling and spec_draft is not None)
                      else 0)
        # -- paged KV pool (paged=True): the slot pool becomes a flat
        #    arena of block_size-token blocks + per-request block tables;
        #    blocks are allocated on demand (capacity = tokens in flight,
        #    not num_slots × max_len) and — non-rolling — shared across
        #    requests through the radix prefix index.  Default kv_blocks
        #    matches the dense pool's capacity exactly, so paged=True
        #    alone changes layout, not limits.
        self.paged = bool(paged)
        if int(block_size) < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = int(block_size)
        self._pool = None
        self._plans: Dict[int, _BlockPlan] = {}
        if self.paged:
            bs = self.block_size
            if self.rolling:
                windows = {layer._mha().attention_window
                           for layer in self.model.layers
                           if hasattr(layer, "_mha")}
                if len(windows) != 1:
                    raise ValueError(
                        "paged rolling pools need one uniform "
                        "attention_window across every TransformerBlock "
                        f"(the block table is per-request, shared by all "
                        f"layers); got windows {sorted(windows)}")
                self._t_view = min(windows.pop() + ring_slack, self.max_len)
            else:
                self._t_view = self.max_len
            self._blocks_per_slot = -(-self._t_view // bs)
            if self._draft_model is not None:
                self._blocks_per_slot = max(self._blocks_per_slot,
                                            -(-self.max_len // bs))
            if kv_blocks is None:
                kv_blocks = self.num_slots * self._blocks_per_slot
            self.kv_blocks = int(kv_blocks)
            if self.kv_blocks < self._blocks_per_slot:
                raise ValueError(
                    f"kv_blocks {self.kv_blocks} cannot hold even one "
                    f"max-length request ({self._blocks_per_slot} blocks "
                    f"of {bs} tokens)")
            self.caches = _dec.init_paged_arena(self.model, self.kv_blocks,
                                                bs, kv_dtype=kv_dtype)
            if self._draft_model is not None:
                self.d_caches = _dec.init_paged_arena(
                    self._draft_model, self.kv_blocks, bs,
                    kv_dtype=kv_dtype)
            else:
                self.d_caches = None
        else:
            self.kv_blocks = None
            self.caches = init_cache(self.model, self.num_slots,
                                     self.max_len, rolling=self.rolling,
                                     kv_dtype=kv_dtype,
                                     ring_slack=ring_slack)
            if self._draft_model is not None:
                self.d_caches = init_cache(self._draft_model,
                                           self.num_slots, self.max_len,
                                           kv_dtype=kv_dtype)
            else:
                self.d_caches = None
        self._handles: List[Optional[RequestHandle]] = [None] * self.num_slots
        self._free: List[int] = list(range(self.num_slots - 1, -1, -1))
        self._positions = np.zeros((self.num_slots,), np.int32)
        self._cur_tok = np.zeros((self.num_slots,), np.int32)
        self._active = np.zeros((self.num_slots,), bool)
        self._temp = np.zeros((self.num_slots,), np.float32)
        self._topk = np.zeros((self.num_slots,), np.int32)    # 0 = off
        self._topp = np.zeros((self.num_slots,), np.float32)  # 0 = off
        self._keys = np.zeros((self.num_slots, 2), np.uint32)

        # -- admission queues (the ONLY cross-thread state besides
        #    handles): one FIFO list per tenant, picked by stride-based
        #    weighted-fair scheduling (interactive-tier tenants first).
        #    With no policies registered everything lands in the single
        #    "default" queue and every pick is plain FIFO — bit-identical
        #    to the pre-QoS deque.  _qdepth is the global depth (the
        #    backpressure bound stays engine-wide); _q_int counts queued
        #    interactive-tier requests (the preemption-pressure signal).
        self._queues: Dict[str, List[RequestHandle]] = {}
        self._qdepth = 0
        self._q_int = 0
        self._wf_pass: Dict[str, float] = {}
        self._tenants: Dict[str, TenantPolicy] = {}
        for pol in (tenants or []):
            if not isinstance(pol, TenantPolicy):
                raise ValueError(f"tenants must be TenantPolicy instances, "
                                 f"got {type(pol).__name__}")
            self._tenants[pol.name] = pol
        self._qlock = threading.Lock()
        self._not_full = threading.Condition(self._qlock)
        self._have_work = threading.Condition(self._qlock)
        self._next_id = 0

        # -- preemption state (QoS swap-out): suspended requests live here
        #    holding NO slot and NO arena blocks — just a host-memory copy
        #    of their live KV blocks + decode frontier.  Scheduler-thread
        #    confined except for the read in _declare_dead (same snapshot
        #    discipline as _handles there).  _preempt_ids carries explicit
        #    preempt() marks to the scheduler; _int_blocked is set when an
        #    interactive admission failed on BLOCK exhaustion (free slot,
        #    empty arena) so starvation-triggered preemption also fires on
        #    pool pressure, not just slot pressure.
        self._suspended: Dict[int, _SuspendedReq] = collections.OrderedDict()
        self._preempt_ids: set = set()
        self._int_blocked = False
        self._can_preempt = (self.paged and not self.rolling
                             and self._draft_model is None
                             and self.role == "unified"
                             and self.prefill_mode == "bucketed")
        self._swap_gather_fn = None
        self._swap_ingest_fn = None

        # -- jitted programs (compiled once per engine: shapes are fixed)
        self._step_fn = self._build_step_fn()
        self._write_slot_fn = jax.jit(
            lambda big, row, s: tmap(
                lambda B, r: jax.lax.dynamic_update_slice(
                    B, r, (s, 0, 0, 0)), big, row),
            donate_argnums=(0,))

        # -- compiled prefill fast path + device-resident decode state
        #    (bucketed mode; the eager reference keeps the host arrays
        #    above authoritative and uploads them every step)
        self._chunk_width = min(self.prefill_chunk, self.max_len)
        self._buckets = _pow2_buckets(self._chunk_width)
        self._pending: "collections.deque" = collections.deque()
        self._prefilling: Dict[int, _PrefillJob] = {}
        self._lookahead = 1 if self.prefill_mode == "bucketed" else 0
        if self.prefill_mode == "bucketed":
            # params live on device once: the decode loop must not re-ship
            # the weights (or anything else) host→device per iteration
            self.params = jax.device_put(self.params)
            self._dev_tok = jnp.zeros((self.num_slots,), jnp.int32)
            self._dev_pos = jnp.zeros((self.num_slots,), jnp.int32)
            self._dev_act = jnp.zeros((self.num_slots,), bool)
            self._dev_temp = jnp.zeros((self.num_slots,), jnp.float32)
            self._dev_topk = jnp.zeros((self.num_slots,), jnp.int32)
            self._dev_topp = jnp.zeros((self.num_slots,), jnp.float32)
            self._dev_keys = jnp.zeros((self.num_slots, 2), jnp.uint32)
            if self.paged:
                # device-resident block tables (one row per slot, null-
                # filled — null = kv_blocks, the arena's junk block) plus
                # the host-side allocator/prefix-trie.  A retired slot's
                # table row is re-nulled so its idle decode passes junk
                # into the null block, never a reallocated block.
                bs = self.block_size
                self._t_tbl = -(-self._t_view // bs) + 1
                self._dev_bt = jnp.full((self.num_slots, self._t_tbl),
                                        self.kv_blocks, jnp.int32)
                if self._draft_model is not None:
                    self._d_tbl = -(-self.max_len // bs) + 1
                    self._dev_dbt = jnp.full(
                        (self.num_slots, self._d_tbl), self.kv_blocks,
                        jnp.int32)
                else:
                    self._dev_dbt = None
                self._copy_fn = self._build_copy_fn()
                if self.role == "prefill":
                    # read-only arena gather (the extraction half of a
                    # disaggregated transfer) — fixed (blocks_per_slot ×
                    # block_size) row vector, so one trace serves every
                    # prompt length (junk rows gather the null block and
                    # are sliced off on host)
                    self._gather_fn = jax.jit(_dec.gather_blocks)
                if self.role == "decode":
                    self._ingest_fn = self._build_ingest_fn()
            self._decode_fn = self._build_device_step_fn()
            self._deact_fn = self._build_deact_fn()
            self._bucket_fns: Dict[int, Any] = {}
            self._stage_fns: Dict[int, Any] = {}
            self._final_fns: Dict[int, Any] = {}
            if self._draft_model is not None:
                self._draft_params = jax.device_put(self._draft_params)
                self._spec_fn = self._build_spec_fn()

        # -- hot weight reload (stretch; off unless attach_ps is called)
        self._ps_addr: Optional[Tuple[str, int]] = None
        self._reload_every = 0
        self._reload_sock: Optional[socket.socket] = None
        self._reload_pool = networking.BufferPool()
        self._reload_policy = None          # resilience.RetryPolicy or None
        #: sharded attachment (attach_ps shard_plan/shard_addrs): pulls
        #: gather the center across every shard through a ShardedPSClient
        #: instead of one socket's 'p'
        self._ps_shard_plan = None
        self._ps_shard_addrs: Optional[List[Tuple[str, int]]] = None
        self._reload_client = None          # ps_sharding.ShardedPSClient
        #: optional (t_monotonic, center_clock) callback fired after every
        #: SUCCESSFUL pull — the freshness seam deployment_online.py hooks
        #: (called on the decode thread; must be cheap and non-raising)
        self._reload_listener = None

        # -- scheduler thread + stats + failure state
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._draining = False
        self._dead: Optional[BaseException] = None  # the EngineDead cause
        #: decode-loop heartbeat (monotonic): stamped once per scheduler
        #: iteration, idle iterations included — a stale beat means the
        #: loop is wedged inside a decode step (EngineSupervisor watches it)
        self.last_beat = time.monotonic()
        self.stats: Dict[str, Any] = {
            "requests_submitted": 0, "requests_completed": 0,
            "requests_rejected": 0, "tokens_generated": 0,
            "prefills": 0, "decode_steps": 0, "active_slot_steps": 0,
            "queue_peak": 0, "slot_requests": [0] * self.num_slots,
            "weight_reloads": 0,
            # hot-reload hardening observables (docs/serving.md): reloads
            # mirrors weight_reloads (successful pulls — both kept so
            # pre-existing consumers and the online-deployment stats agree),
            # reload_failures counts pulls abandoned after the retry
            # policy's re-dial budget, center_generation is the PS center's
            # update clock stamped on the last successful pull (None until
            # one lands) — the commit→pull→decode generation chain
            # deployment_online.py tracks freshness through
            "reloads": 0, "reload_failures": 0, "center_generation": None,
            # failure-semantics observables (this PR's contract surface):
            # cancelled/expired count retirements by reason; failed counts
            # handles the engine abandoned with EngineDead; reclaim_ms is
            # one sample per mid-run cancel/deadline slot reclamation
            # (cancel/expiry instant → slot free)
            "requests_cancelled": 0, "requests_expired": 0,
            "requests_failed": 0, "slot_reclaim_ms": [],
            # prefill fast-path observables: chunk-program invocations,
            # batched-prefill width (mean admitted requests per bucket
            # program call), prompt tokens prefilled, and the decode
            # loop's transfer discipline (decode-only iterations perform
            # zero h2d and exactly one d2h — the sampled token row)
            "prefill_chunks": 0, "prefill_batches": 0,
            "prefill_batched_requests": 0, "prefill_batch_size_mean": None,
            "prefill_tokens": 0,
            "h2d_transfers": 0, "d2h_transfers": 0,
            # speculative-decoding observables, the same vocabulary as
            # speculative_generate's per-run stats dict: ``drafted`` /
            # ``accepted`` count draft proposals and accepted prefix
            # tokens, ``verify_calls`` the batched target verify forwards
            # (``target_calls`` mirrors it verbatim so offline and serving
            # speculation report through one key set)
            "drafted": 0, "accepted": 0,
            "verify_calls": 0, "target_calls": 0,
            # paged-pool observables: blocks_allocated counts fresh
            # allocations, blocks_reused trie-shared blocks, prefix_hits/
            # prefix_hit_tokens admissions (and their token counts) served
            # from the radix index, cow_copies boundary copy-on-writes,
            # blocks_evicted LRU reclaims of refcount-0 cached chains.
            # kv_pool_bytes is the on-device pool footprint gauge (arena
            # bytes when paged, the dense slot pool's otherwise) — the
            # byte-accounting that proves block reuse next to PR 11's
            # kv_cache_bytes math
            "blocks_allocated": 0, "blocks_reused": 0, "blocks_evicted": 0,
            "prefix_hits": 0, "prefix_hit_tokens": 0, "cow_copies": 0,
            "kv_pool_bytes": _quant.kv_cache_bytes(self.caches),
            # disaggregation transfer accounting (charged against the
            # PR 9 transfer-discipline counters — gather fetches and
            # scatter uploads land in d2h/h2d_transfers too):
            # kv_blocks_shipped/_bytes count blocks a prefill-role engine
            # extracted, kv_blocks_ingested/_bytes blocks a decode-role
            # engine admitted from a shipped set; transfer_ms is one
            # sample per extraction/ingest (device dispatch + host copy)
            "kv_blocks_shipped": 0, "kv_block_bytes_shipped": 0,
            "kv_blocks_ingested": 0, "kv_block_bytes_ingested": 0,
            "transfer_ms": [],
            # multi-tenant QoS observables: preemptions/resumes count
            # swap-out/swap-in events; the block/byte counters account the
            # swapped KV payloads (their d2h/h2d dispatches also land in
            # the PR 9 transfer-discipline counters); preempt_swap_ms /
            # preempt_resume_ms are one sample per suspend / resume
            # (device gather/ingest + host copy); quota_refused counts
            # token-bucket admission refusals (NOT requests_rejected —
            # a policy refusal must not dilute shed_rate); "tenants" maps
            # tenant name -> its own submitted/completed/shed/
            # quota_refused/preemptions/resumes counters
            "preemptions": 0, "resumes": 0,
            "kv_blocks_swapped_out": 0, "kv_block_bytes_swapped_out": 0,
            "kv_blocks_resumed": 0, "kv_block_bytes_resumed": 0,
            "preempt_swap_ms": [], "preempt_resume_ms": [],
            "quota_refused": 0, "tenants": {},
        }
        if self.paged:
            self._pool = _PagedKVPool(self.kv_blocks, self.block_size,
                                      share=not self.rolling,
                                      stats=self.stats)

        # -- lock-free load snapshot (the routing surface).  A plain dict
        #    republished by REFERENCE assignment from submit/step/drain/
        #    death sites — always OUTSIDE the admission-lock blocks, so
        #    readers (`load()`, a ServingRouter's dispatch loop, the wire
        #    's' probe) never touch `_qlock` or the scheduler's hot path.
        #    Values may lag one scheduler iteration; routing only needs a
        #    recent signal, not a linearizable one.
        self._load_snapshot: Dict[str, Any] = {
            "queue_depth": 0,
            "slots_free": self.num_slots,
            "slots_total": self.num_slots,
            "active": 0,
            "trie_blocks": 0,
            "queue_capacity": self.queue_capacity,
            "max_len": self.max_len,
            "draining": False,
            "dead": False,
            "prefix_hit_tokens": 0,
            "prefill_tokens": 0,
            "tokens_generated": 0,
            "requests_completed": 0,
            "requests_failed": 0,
            "queued_interactive": 0,
        }

    # ------------------------------------------------------------------ jit
    def _build_step_fn(self):
        model, rolling = self.model, self.rolling

        def step(params, caches, tok, positions, active, temp, topk, topp,
                 keys):
            logits, caches = decode_step(model, params, caches, tok,
                                         positions, rolling)
            nxt = sample_logits_batched(logits, positions, temp, keys,
                                        topk, topp)
            # active mask: free slots keep their token (their row computes a
            # junk forward into their own cache row, which the next
            # prefill fully overwrites — never into anyone else's)
            return jnp.where(active, nxt, tok), caches

        return jax.jit(step, donate_argnums=(1,))

    # ------------------------------------------- compiled prefill programs
    #
    # The fast path's whole compute surface is a handful of jitted
    # programs, cached per shape key so live traffic never re-traces:
    #
    #  - ``_bucket_fn(L)`` — ONE batched forward prefills up to
    #    ``prefills_per_step`` admitted prompts right-padded to bucket
    #    length L, samples each row's first token, scatters the cache rows
    #    into the pool (ring-converted per row for rolling engines) and
    #    the per-slot decode state in the same program.  Unused batch rows
    #    carry slot index ``num_slots``: every one of their writes drops
    #    (``mode="drop"``), which is also what makes ``warmup()``'s
    #    precompilation side-effect free.
    #  - ``_stage_fn(C)`` / ``_final_fn(C)`` — chunked prefill: chunks
    #    accumulate into a full-length one-row STAGING cache (``q_offset``
    #    = the chunk offset, exactly the scalar decode-walker path); the
    #    final chunk samples the first token and commits the whole row to
    #    the pool in one program (ring-collapsed via ``ring_from_prefill``
    #    for rolling engines, a full-row overwrite otherwise).  Staging is
    #    NOT optional: the per-row decode step writes junk k/v into every
    #    pool row at its stale position — free and prefilling slots
    #    included — which an atomic full-row commit overwrites but an
    #    in-place chunk accumulation would race (a junk write at a stale
    #    position below the chunk frontier corrupts already-written
    #    prompt positions).
    #
    # Every traced call goes through ``_dec`` (the decode MODULE) so a
    # trace is observable/countable; the module-level ``_forward`` import
    # is the EAGER path's — the bucketed hot path never calls it.

    def _bucket_fn(self, width: int):
        fn = self._bucket_fns.get(width)
        if fn is None:
            fn = self._bucket_fns[width] = self._build_bucket_fn(width)
        return fn

    def _stage_fn(self, width: int):
        fn = self._stage_fns.get(width)
        if fn is None:
            fn = self._stage_fns[width] = self._build_stage_fn(width)
        return fn

    def _final_fn(self, width: int):
        fn = self._final_fns.get(width)
        if fn is None:
            fn = self._final_fns[width] = self._build_final_fn(width)
        return fn

    def _build_device_step_fn(self):
        """The bucketed-mode decode step: state advances ON DEVICE (donated
        caches, new positions), so a steady-state iteration uploads nothing
        and reads back only the sampled token row.  Paged engines take the
        device block tables as an extra (read-only) argument and write/
        gather through them — per-row block-indexed cache writes inside
        the same jitted step."""
        model, rolling = self.model, self.rolling

        if self.paged:
            page, view = self.block_size, self._t_view

            def pstep(params, caches, bt, tok, positions, active, temp,
                      topk, topp, keys):
                pv = _dec.PagedView(bt, page, view, ring=rolling)
                logits, caches = _dec.decode_step(model, params, caches,
                                                  tok, positions, paged=pv)
                nxt = _dec.sample_logits_batched(logits, positions, temp,
                                                 keys, topk, topp)
                out = jnp.where(active, nxt, tok)
                positions = jnp.where(active, positions + 1, positions)
                return out, caches, positions

            return jax.jit(pstep, donate_argnums=(1, 4))

        def step(params, caches, tok, positions, active, temp, topk, topp,
                 keys):
            logits, caches = _dec.decode_step(model, params, caches, tok,
                                              positions, rolling)
            nxt = _dec.sample_logits_batched(logits, positions, temp, keys,
                                             topk, topp)
            out = jnp.where(active, nxt, tok)
            positions = jnp.where(active, positions + 1, positions)
            return out, caches, positions

        return jax.jit(step, donate_argnums=(1, 3))

    def _build_deact_fn(self):
        """Slot retirement on device: clear the active flag and — paged —
        re-null the slot's block-table row(s), so the retired row's idle
        decode junk drops into the null block instead of blocks the
        allocator may already have handed to a new request."""
        if not self.paged:
            return jax.jit(lambda act, slot: act.at[slot].set(False))
        null = jnp.int32(self.kv_blocks)
        if self._draft_model is None:
            return jax.jit(lambda act, bt, slot: (
                act.at[slot].set(False), bt.at[slot].set(null)))
        return jax.jit(lambda act, bt, dbt, slot: (
            act.at[slot].set(False), bt.at[slot].set(null),
            dbt.at[slot].set(null)))

    def _build_copy_fn(self):
        """The copy-on-write program: duplicate one physical block (all
        layers, target AND draft arenas) so an admission that matched a
        cached block PARTIALLY can keep writing its own suffix into the
        copy while the original stays shared."""
        bs = self.block_size

        def copy_one(caches, src, dst):
            def cp(leaf):
                row = jax.lax.dynamic_slice_in_dim(leaf, src * bs, bs, 0)
                return jax.lax.dynamic_update_slice_in_dim(leaf, row,
                                                           dst * bs, 0)
            return [None if c is None else {k: cp(v) for k, v in c.items()}
                    for c in caches]

        if self._draft_model is None:
            return jax.jit(copy_one, donate_argnums=(0,))

        def copy_both(caches, dcaches, src, dst):
            return copy_one(caches, src, dst), copy_one(dcaches, src, dst)

        return jax.jit(copy_both, donate_argnums=(0, 1))

    def _build_ingest_fn(self):
        """Decode-role admission program, ONE jitted dispatch per shipped
        request: scatter the transferred block payload into this engine's
        own arena slots (``rows`` — junk rows padded to the null block, so
        the shape is fixed at ``blocks_per_slot × block_size``) and
        install the slot's device row (block table, current token at the
        shipped position, sampling params, RNG key) exactly as a bucket
        prefill program would have.  ``mode="drop"`` on every install
        lets warmup target slot ``num_slots``."""
        def ingest(caches, bt, tok, pos, act, temp, topk, topp, keys,
                   rows, payload, slot, row_bt, r_tok, r_pos, r_temp,
                   r_topk, r_topp, r_keys):
            caches = _dec.scatter_blocks(caches, rows, payload)
            bt = bt.at[slot].set(row_bt, mode="drop")
            tok = tok.at[slot].set(r_tok, mode="drop")
            pos = pos.at[slot].set(r_pos, mode="drop")
            act = act.at[slot].set(True, mode="drop")
            temp = temp.at[slot].set(r_temp, mode="drop")
            topk = topk.at[slot].set(r_topk, mode="drop")
            topp = topp.at[slot].set(r_topp, mode="drop")
            keys = keys.at[slot].set(r_keys, mode="drop")
            return caches, bt, tok, pos, act, temp, topk, topp, keys

        # tok (argnum 2) is NOT donated: with one-step lookahead the live
        # ``_dev_tok`` IS the previous decode step's still-pending output
        # array — donating it would delete the buffer ``_drain_pending``
        # has yet to fetch (the same reason no decode/prefill program
        # donates its token state)
        return jax.jit(ingest, donate_argnums=(0, 1, 3, 4, 5, 6, 7, 8))

    def _build_spec_fn(self):
        """The speculative decode round — ONE jitted program replacing the
        plain device step when ``spec_draft`` is set: k = ``spec_len``
        per-row draft steps (the draft's own slot pool, same slot
        indices), ONE batched L = k + 1 target verify forward, per-row
        accept/commit — greedy rows take the longest drafted prefix
        matching the target's own argmax plus the correction/bonus token
        (so their committed chain IS the target argmax chain, token-
        identical to non-speculative greedy); sampled rows run the
        Leviathan/Chen rejection rule against identically-warped
        distributions with keys folded per (position, purpose), so the
        committed distribution is exactly the warped target's — then a
        draft back-fill step for the full-accept cache hole and the
        device state advance.  Accept lengths are heterogeneous: row r
        advances ``n_r`` in 1..k+1 positions per round.  The output packs
        row r's committed tokens (first ``n_r`` of k+1 columns valid)
        plus ``n_r`` in the last column — ONE drained array per round,
        preserving the one-d2h-per-iteration discipline.

        Rejected-position cache entries are never rolled back: the next
        round's writes start at each row's new frontier and overwrite
        them in-program before any query can attend that far (the same
        no-rollback argument as ``speculative_generate``; on rolling
        pools the ring's ``spec_len`` slack slots keep the oldest query's
        window intact under the L-token write)."""
        model, rolling = self.model, self.rolling
        draft = self._draft_model
        k = self.spec_len
        paged = self.paged
        page = self.block_size
        t_view = self._t_view if paged else None
        d_view = self.max_len

        def fold(keys, idx, tag):
            # per-(row, absolute position, purpose) keys: tag 1 = draft
            # proposal, 2 = accept uniform, 3 = residual/bonus draw.  A
            # position's draws are pure functions of (request key, index),
            # so re-drafting an index after a rejection reuses bits that
            # never influenced any committed token — exactness holds
            ks = jax.vmap(jax.random.fold_in)(keys, idx)
            return jax.vmap(jax.random.fold_in)(ks, jnp.full_like(idx, tag))

        def round_(params, dparams, caches, dcaches, tok, pos, act, temp,
                   topk, topp, keys, bt=None, dbt=None):
            b = tok.shape[0]
            sampled = temp > 0.0
            safe_t = jnp.where(sampled, temp, 1.0)
            # paged pools: the round's every cache access goes through the
            # slot block tables (read-only here — allocation is host-side)
            pv_t = (_dec.PagedView(bt, page, t_view, ring=rolling)
                    if bt is not None else None)
            pv_d = (_dec.PagedView(dbt, page, d_view)
                    if dbt is not None else None)

            def warp(l):
                return _dec.filter_logits_batched(l / safe_t[:, None],
                                                  topk, topp)

            # -- draft phase: k per-row single-token steps, own pool
            d_toks, q_logits = [], []
            t = tok
            for i in range(k):
                dl, dcaches = _dec.decode_step(draft, dparams, dcaches, t,
                                               pos + i, paged=pv_d)
                wl = warp(dl)
                prop = jax.vmap(jax.random.categorical)(
                    fold(keys, pos + i + 1, 1), wl).astype(jnp.int32)
                t = jnp.where(sampled, prop,
                              jnp.argmax(dl, axis=-1).astype(jnp.int32))
                d_toks.append(t)
                q_logits.append(wl)
            drafted = jnp.stack(d_toks, axis=1)                   # (B, k)

            # -- verify: one batched target forward over [cur, d_1..d_k];
            # logits[:, i] scores the token following fed position i, so a
            # fully-accepted row still has a bonus distribution at index k
            fed = jnp.concatenate([tok[:, None], drafted], axis=1)
            logits, caches = _dec._forward(model, params, caches, fed, pos,
                                           rolling and pv_t is None,
                                           paged=pv_t)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            # greedy accept: longest drafted prefix matching the target's
            # argmax; the committed chain is the argmax chain itself
            match = drafted == greedy[:, :k]
            a_g = jnp.sum(jnp.cumprod(match.astype(jnp.int32), 1), 1)

            # sampled accept: accept x ~ q with prob min(1, p(x)/q(x));
            # first rejection redraws from norm(max(p - q, 0)), a full
            # accept draws the bonus from warped p — all per row
            pk = jnp.reshape(_dec.filter_logits_batched(
                jnp.reshape(logits[:, :k] / safe_t[:, None, None],
                            (b * k, -1)),
                jnp.repeat(topk, k), jnp.repeat(topp, k)), (b, k, -1))
            p_probs = jax.nn.softmax(pk, axis=-1)
            q_probs = jax.nn.softmax(jnp.stack(q_logits, 1), axis=-1)
            px = jnp.take_along_axis(p_probs, drafted[..., None],
                                     axis=-1)[..., 0]
            qx = jnp.take_along_axis(q_probs, drafted[..., None],
                                     axis=-1)[..., 0]
            u = jnp.stack(
                [jax.vmap(lambda kk: jax.random.uniform(kk, ()))(
                    fold(keys, pos + i + 1, 2)) for i in range(k)], axis=1)
            accept = u * jnp.maximum(qx, 1e-30) < px
            a_s = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), 1), 1)
            ai = jnp.clip(a_s, 0, k - 1)
            p_a = jnp.take_along_axis(p_probs, ai[:, None, None], 1)[:, 0]
            q_a = jnp.take_along_axis(q_probs, ai[:, None, None], 1)[:, 0]
            res = jnp.maximum(p_a - q_a, 0.0)
            rsum = jnp.sum(res, axis=-1, keepdims=True)
            # res == 0 iff p <= q everywhere, i.e. p == q: fall back to p
            res = jnp.where(rsum > 0.0, res / jnp.maximum(rsum, 1e-38),
                            p_a)
            bonus = jax.nn.softmax(warp(logits[:, k]), axis=-1)
            dist = jnp.where((a_s == k)[:, None], bonus, res)
            corr = jax.vmap(jax.random.categorical)(
                fold(keys, pos + a_s + 1, 3),
                jnp.log(jnp.maximum(dist, 1e-38))).astype(jnp.int32)
            committed_s = jnp.concatenate(
                [drafted, jnp.zeros((b, 1), jnp.int32)], axis=1)
            committed_s = committed_s.at[jnp.arange(b), a_s].set(corr)

            # -- per-row heterogeneous commit + device state advance
            a = jnp.where(sampled, a_s, a_g)
            committed = jnp.where(sampled[:, None], committed_s, greedy)
            n = jnp.where(act, a + 1, 0)
            last = jnp.take_along_axis(committed, a[:, None], axis=1)[:, 0]
            new_tok = jnp.where(act, last, tok)
            new_pos = jnp.where(act, pos + n, pos)

            # draft back-fill: d_k at pos + k — the full-accept rows' cache
            # hole (the committed bonus's predecessor, never fed to the
            # draft); for every other row pos + k is at or past its new
            # frontier, where the junk is masked until overwritten
            _, dcaches = _dec.decode_step(draft, dparams, dcaches,
                                          d_toks[-1], pos + k, paged=pv_d)

            out = jnp.concatenate([committed, n[:, None]], axis=1)
            return out, caches, dcaches, new_tok, new_pos

        if paged:
            def round_paged(params, dparams, caches, dcaches, bt, dbt,
                            tok, pos, act, temp, topk, topp, keys):
                return round_(params, dparams, caches, dcaches, tok, pos,
                              act, temp, topk, topp, keys, bt=bt, dbt=dbt)

            return jax.jit(round_paged, donate_argnums=(2, 3, 6, 7))

        return jax.jit(round_, donate_argnums=(2, 3, 4, 5))

    def _build_bucket_fn(self, width: int):
        if self.paged:
            return self._build_paged_bucket_fn(width)
        model, rolling = self.model, self.rolling
        draft = self._draft_model

        def prefill(params, dparams, pool, dpool, tok, pos, act, temp,
                    topk, topp, keys, prompts, p_lens, slots, r_temp,
                    r_topk, r_topp, r_keys):
            rows = init_cache(model, prompts.shape[0], width)
            # right-padded batch: the causal mask alone keeps pad keys out
            # of every real row (see _mha_forward), and the pad slots each
            # row's prefill writes stay behind its decode kv_length
            # frontier until overwritten
            logits, rows = _dec._forward(model, params, rows, prompts, 0)
            idx = jnp.clip(p_lens - 1, 0, width - 1)
            last = jnp.take_along_axis(logits, idx[:, None, None],
                                       axis=1)[:, 0]
            first = _dec.sample_logits_batched(last, p_lens - 1, r_temp,
                                               r_keys, r_topk, r_topp)
            out = [first,
                   [_commit_rows(big, row, slots, width, rolling, p_lens)
                    for big, row in zip(pool, rows)]]
            if draft is not None:
                # the draft shares the slot layout: prefill its pool from
                # the same prompts (logits unused — the draft's LM head
                # dead-code-eliminates out of this program)
                drows = init_cache(draft, prompts.shape[0], width)
                _, drows = _dec._forward(draft, dparams, drows, prompts, 0)
                out.append(
                    [_commit_rows(big, row, slots, width, False, p_lens)
                     for big, row in zip(dpool, drows)])
            out += [tok.at[slots].set(first, mode="drop"),
                    pos.at[slots].set(p_lens, mode="drop"),
                    act.at[slots].set(True, mode="drop"),
                    temp.at[slots].set(r_temp, mode="drop"),
                    topk.at[slots].set(r_topk, mode="drop"),
                    topp.at[slots].set(r_topp, mode="drop"),
                    keys.at[slots].set(r_keys, mode="drop")]
            return tuple(out)

        if draft is not None:
            return jax.jit(prefill, donate_argnums=(2, 3))

        def run(params, pool, *rest):
            return prefill(params, None, pool, None, *rest)

        return jax.jit(run, donate_argnums=(1,))

    def _build_paged_bucket_fn(self, width: int):
        """The paged bucket program.  Non-rolling: the batch prefills its
        UNMATCHED SUFFIXES directly into the arena through per-row block
        tables — each row's queries start at its matched length, attend
        the shared prefix blocks through the block-table gather (rows
        admitted in the same program read each other's just-written
        prefix: the layer's scatter covers every row before its gather),
        and write with a ``floor`` at the matched frontier so shared
        blocks are never touched.  Rolling: the dense prefill +
        ``ring_from_prefill`` relayout commits through the block table
        instead of into pool rows (no sharing on rings — ring layout is
        position-dependent).  Either way the program also installs the
        slot rows of the DEVICE block tables, so decode needs no
        per-iteration upload."""
        model, rolling = self.model, self.rolling
        draft = self._draft_model
        page, t_view, d_view = self.block_size, self._t_view, self.max_len

        def prefill(params, dparams, pool, dpool, bt, dbt, tok, pos, act,
                    temp, topk, topp, keys, prompts, match, p_lens, slots,
                    row_bt, row_dbt, r_temp, r_topk, r_topp, r_keys):
            if not rolling:
                pv = _dec.PagedView(row_bt, page, t_view, floor=match,
                                    ceil=p_lens, qcap=p_lens - 1)
                logits, pool = _dec._forward(model, params, pool, prompts,
                                             match, paged=pv)
                idx = jnp.clip(p_lens - match - 1, 0, width - 1)
                last = jnp.take_along_axis(logits, idx[:, None, None],
                                           axis=1)[:, 0]
            else:
                rows = init_cache(model, prompts.shape[0], width)
                logits, rows = _dec._forward(model, params, rows, prompts,
                                             0)
                idx = jnp.clip(p_lens - 1, 0, width - 1)
                last = jnp.take_along_axis(logits, idx[:, None, None],
                                           axis=1)[:, 0]
                j = jnp.arange(t_view)
                blk = jnp.minimum(j // page, row_bt.shape[1] - 1)
                phys = (jnp.take(row_bt, blk, axis=1) * page
                        + (j % page)[None, :])
                new_pool = []
                for big, row in zip(pool, rows):
                    if big is None:
                        new_pool.append(None)
                        continue
                    rk = _dec.ring_from_prefill(row["k"], p_lens, t_view)
                    rv = _dec.ring_from_prefill(row["v"], p_lens, t_view)
                    new_pool.append(_dec._kv_write(big, (phys,), rk, rv))
                pool = new_pool
            first = _dec.sample_logits_batched(last, p_lens - 1, r_temp,
                                               r_keys, r_topk, r_topp)
            out = [first, pool]
            if draft is not None:
                # the draft pool is always full-view (non-rolling): its
                # prefill runs arena-direct per-row whatever the target's
                # layout — match is 0 for rolling targets (no sharing)
                pv_d = _dec.PagedView(row_dbt, page, d_view, floor=match,
                                      ceil=p_lens, qcap=p_lens - 1)
                _, dpool = _dec._forward(draft, dparams, dpool, prompts,
                                         match, paged=pv_d)
                out.append(dpool)
            out.append(bt.at[slots].set(row_bt, mode="drop"))
            if draft is not None:
                out.append(dbt.at[slots].set(row_dbt, mode="drop"))
            out += [tok.at[slots].set(first, mode="drop"),
                    pos.at[slots].set(p_lens, mode="drop"),
                    act.at[slots].set(True, mode="drop"),
                    temp.at[slots].set(r_temp, mode="drop"),
                    topk.at[slots].set(r_topk, mode="drop"),
                    topp.at[slots].set(r_topp, mode="drop"),
                    keys.at[slots].set(r_keys, mode="drop")]
            return tuple(out)

        if draft is not None:
            return jax.jit(prefill, donate_argnums=(2, 3, 4, 5))

        def run(params, pool, bt, tok, pos, act, temp, topk, topp, keys,
                prompts, match, p_lens, slots, row_bt,
                r_temp, r_topk, r_topp, r_keys):
            return prefill(params, None, pool, None, bt, None, tok, pos,
                           act, temp, topk, topp, keys, prompts, match,
                           p_lens, slots, row_bt, None, r_temp, r_topk,
                           r_topp, r_keys)

        return jax.jit(run, donate_argnums=(1, 2))

    def _build_stage_fn(self, width: int):
        if self.paged and not self.rolling:
            return self._build_paged_stage_fn(width)
        model, draft = self.model, self._draft_model

        def stage(params, staging, toks, offset):
            # mid chunk: cache writes only — the logits (and the whole
            # LM-head matmul) dead-code-eliminate
            _, staging = _dec._forward(model, params, staging, toks, offset)
            return staging

        if draft is None:
            return jax.jit(stage, donate_argnums=(1,))

        def stage_spec(params, dparams, staging, d_staging, toks, offset):
            _, staging = _dec._forward(model, params, staging, toks, offset)
            _, d_staging = _dec._forward(draft, dparams, d_staging, toks,
                                         offset)
            return staging, d_staging

        return jax.jit(stage_spec, donate_argnums=(2, 3))

    def _build_paged_stage_fn(self, width: int):
        """Paged (non-rolling) chunked prefill: chunks write STRAIGHT into
        the request's allocated blocks (no staging cache — the blocks are
        private by construction, and the slot's device table stays null
        until the final chunk, so nothing else can write them).  The
        chunk's queries attend every earlier position — shared prefix
        included — through the block-table gather."""
        model, draft = self.model, self._draft_model
        page, t_view, d_view = self.block_size, self._t_view, self.max_len

        def stage(params, pool, toks, offset, p_len, row_bt):
            pv = _dec.PagedView(row_bt, page, t_view, floor=offset,
                                ceil=p_len, qcap=p_len - 1)
            _, pool = _dec._forward(model, params, pool, toks, offset,
                                    paged=pv)
            return pool

        if draft is None:
            return jax.jit(stage, donate_argnums=(1,))

        def stage_spec(params, dparams, pool, dpool, toks, offset, p_len,
                       row_bt, row_dbt):
            pv = _dec.PagedView(row_bt, page, t_view, floor=offset,
                                ceil=p_len, qcap=p_len - 1)
            _, pool = _dec._forward(model, params, pool, toks, offset,
                                    paged=pv)
            pv_d = _dec.PagedView(row_dbt, page, d_view, floor=offset,
                                  ceil=p_len, qcap=p_len - 1)
            _, dpool = _dec._forward(draft, dparams, dpool, toks, offset,
                                     paged=pv_d)
            return pool, dpool

        return jax.jit(stage_spec, donate_argnums=(2, 3))

    def _build_paged_final_fn(self, width: int):
        """Paged (non-rolling) final chunk: last suffix tokens into the
        arena + first-token sample + device state install (block-table
        row included) — the paged twin of the dense final commit, minus
        the staging copy it no longer needs."""
        model, draft = self.model, self._draft_model
        page, t_view, d_view = self.block_size, self._t_view, self.max_len

        def final(params, dparams, pool, dpool, bt, dbt, tok, pos, act,
                  temp, topk, topp, keys, toks, slot, offset, p_len,
                  last_idx, row_bt, row_dbt, r_temp, r_topk, r_topp,
                  r_key):
            pv = _dec.PagedView(row_bt, page, t_view, floor=offset,
                                ceil=p_len, qcap=p_len - 1)
            logits, pool = _dec._forward(model, params, pool, toks, offset,
                                         paged=pv)
            first = _dec.sample_logits_batched(
                logits[0, last_idx][None], p_len - 1, r_temp, r_key,
                r_topk, r_topp)
            out = [first, pool]
            if draft is not None:
                pv_d = _dec.PagedView(row_dbt, page, d_view, floor=offset,
                                      ceil=p_len, qcap=p_len - 1)
                _, dpool = _dec._forward(draft, dparams, dpool, toks,
                                         offset, paged=pv_d)
                out.append(dpool)
            out.append(bt.at[slot].set(row_bt[0], mode="drop"))
            if draft is not None:
                out.append(dbt.at[slot].set(row_dbt[0], mode="drop"))
            out += [tok.at[slot].set(first[0], mode="drop"),
                    pos.at[slot].set(p_len[0], mode="drop"),
                    act.at[slot].set(True, mode="drop"),
                    temp.at[slot].set(r_temp[0], mode="drop"),
                    topk.at[slot].set(r_topk[0], mode="drop"),
                    topp.at[slot].set(r_topp[0], mode="drop"),
                    keys.at[slot].set(r_key[0], mode="drop")]
            return tuple(out)

        if draft is not None:
            return jax.jit(final, donate_argnums=(2, 3, 4, 5))

        def run(params, pool, bt, tok, pos, act, temp, topk, topp, keys,
                toks, slot, offset, p_len, last_idx, row_bt,
                r_temp, r_topk, r_topp, r_key):
            return final(params, None, pool, None, bt, None, tok, pos,
                         act, temp, topk, topp, keys, toks, slot, offset,
                         p_len, last_idx, row_bt, None, r_temp, r_topk,
                         r_topp, r_key)

        return jax.jit(run, donate_argnums=(1, 2))

    def _build_paged_ring_final_fn(self, width: int):
        """Paged ROLLING final chunk: the dense staging cache (rolling
        chunks still stage — a ring commit needs the whole prompt tail at
        once) ring-collapses through ``ring_from_prefill`` and scatters
        into the slot's blocks via its block table; the draft twin (full
        view, non-rolling) commits its staged positions below ``p_len``
        and routes the rest into the null block."""
        model, draft = self.model, self._draft_model
        page, t_view, d_view = self.block_size, self._t_view, self.max_len

        def final(params, dparams, pool, dpool, bt, dbt, tok, pos, act,
                  temp, topk, topp, keys, staging, d_staging, toks, slot,
                  offset, last_idx, p_len, row_bt, row_dbt, r_temp,
                  r_topk, r_topp, r_key):
            logits, staging = _dec._forward(model, params, staging, toks,
                                            offset)
            first = _dec.sample_logits_batched(
                logits[0, last_idx][None], jnp.asarray(p_len - 1)[None],
                r_temp, r_key, r_topk, r_topp)
            p_row = jnp.asarray(p_len)[None]
            j = jnp.arange(t_view)
            blk = jnp.minimum(j // page, row_bt.shape[1] - 1)
            phys = (jnp.take(row_bt, blk, axis=1) * page
                    + (j % page)[None, :])
            new_pool = []
            for big, row in zip(pool, staging):
                if big is None:
                    new_pool.append(None)
                    continue
                rk = _dec.ring_from_prefill(row["k"], p_row, t_view)
                rv = _dec.ring_from_prefill(row["v"], p_row, t_view)
                new_pool.append(_dec._kv_write(big, (phys,), rk, rv))
            out = [first, new_pool]
            if draft is not None:
                _, d_staging = _dec._forward(draft, dparams, d_staging,
                                             toks, offset)
                null_phys = dpool[[i for i, c in enumerate(dpool)
                                   if c is not None][0]]["k"].shape[0] - 1
                jd = jnp.arange(d_view)
                blkd = jnp.minimum(jd // page, row_dbt.shape[1] - 1)
                physd = (jnp.take(row_dbt, blkd, axis=1) * page
                         + (jd % page)[None, :])
                physd = jnp.where(jd[None, :] < p_row[:, None], physd,
                                  null_phys)
                new_dpool = []
                for big, row in zip(dpool, d_staging):
                    if big is None:
                        new_dpool.append(None)
                        continue
                    new_dpool.append(_dec._kv_write(big, (physd,),
                                                    row["k"], row["v"]))
                out.append(new_dpool)
            out.append(bt.at[slot].set(row_bt[0], mode="drop"))
            if draft is not None:
                out.append(dbt.at[slot].set(row_dbt[0], mode="drop"))
            out += [tok.at[slot].set(first[0], mode="drop"),
                    pos.at[slot].set(p_len, mode="drop"),
                    act.at[slot].set(True, mode="drop"),
                    temp.at[slot].set(r_temp[0], mode="drop"),
                    topk.at[slot].set(r_topk[0], mode="drop"),
                    topp.at[slot].set(r_topp[0], mode="drop"),
                    keys.at[slot].set(r_key[0], mode="drop")]
            return tuple(out)

        if draft is not None:
            return jax.jit(final, donate_argnums=(2, 3, 4, 5))

        def run(params, pool, bt, tok, pos, act, temp, topk, topp, keys,
                staging, toks, slot, offset, last_idx, p_len, row_bt,
                r_temp, r_topk, r_topp, r_key):
            return final(params, None, pool, None, bt, None, tok, pos,
                         act, temp, topk, topp, keys, staging, None, toks,
                         slot, offset, last_idx, p_len, row_bt, None,
                         r_temp, r_topk, r_topp, r_key)

        return jax.jit(run, donate_argnums=(1, 2))

    def _build_final_fn(self, width: int):
        if self.paged and not self.rolling:
            return self._build_paged_final_fn(width)
        if self.paged:
            return self._build_paged_ring_final_fn(width)
        model, rolling = self.model, self.rolling
        draft = self._draft_model

        def final(params, dparams, pool, dpool, tok, pos, act, temp, topk,
                  topp, keys, staging, d_staging, toks, slot, offset,
                  last_idx, p_len, r_temp, r_topk, r_topp, r_key):
            logits, staging = _dec._forward(model, params, staging, toks,
                                            offset)
            first = _dec.sample_logits_batched(
                logits[0, last_idx][None], jnp.asarray(p_len - 1)[None],
                r_temp, r_key, r_topk, r_topp)
            p_row = jnp.asarray(p_len)[None]
            # full-row commit: atomically replaces whatever junk the free
            # slot's decode passes wrote while chunks staged
            out = [first,
                   [_commit_full_row(big, row, slot, rolling, p_row)
                    for big, row in zip(pool, staging)]]
            if draft is not None:
                _, d_staging = _dec._forward(draft, dparams, d_staging,
                                             toks, offset)
                out.append([_commit_full_row(big, row, slot, False, p_row)
                            for big, row in zip(dpool, d_staging)])
            out += [tok.at[slot].set(first[0], mode="drop"),
                    pos.at[slot].set(p_len, mode="drop"),
                    act.at[slot].set(True, mode="drop"),
                    temp.at[slot].set(r_temp[0], mode="drop"),
                    topk.at[slot].set(r_topk[0], mode="drop"),
                    topp.at[slot].set(r_topp[0], mode="drop"),
                    keys.at[slot].set(r_key[0], mode="drop")]
            return tuple(out)

        # staging is NOT donated: the ring relayout is a gather whose
        # output shape differs from the staging buffer, so XLA could not
        # reuse it anyway (it dies with the program instead)
        if draft is not None:
            return jax.jit(final, donate_argnums=(2, 3))

        def run(params, pool, tok, pos, act, temp, topk, topp, keys,
                staging, toks, slot, offset, last_idx, p_len,
                r_temp, r_topk, r_topp, r_key):
            return final(params, None, pool, None, tok, pos, act, temp,
                         topk, topp, keys, staging, None, toks, slot,
                         offset, last_idx, p_len, r_temp, r_topk, r_topp,
                         r_key)

        return jax.jit(run, donate_argnums=(1,))

    # ----------------------------------------------------- device traffic
    def _put(self, x):
        """Host→device upload (admission inputs only).  Counted so the
        transfer discipline is assertable: a decode-only iteration
        performs ZERO uploads."""
        self.stats["h2d_transfers"] += 1
        return jnp.asarray(x)

    def _fetch(self, arr) -> np.ndarray:
        """Device→host readback — the ONE transfer per drained step (the
        sampled token row, or a prefill batch's first tokens)."""
        self.stats["d2h_transfers"] += 1
        return np.asarray(arr)

    def _state_args(self):
        if self.paged:
            if self._draft_model is None:
                return (self.caches, self._dev_bt, self._dev_tok,
                        self._dev_pos, self._dev_act, self._dev_temp,
                        self._dev_topk, self._dev_topp, self._dev_keys)
            return (self.caches, self.d_caches, self._dev_bt,
                    self._dev_dbt, self._dev_tok, self._dev_pos,
                    self._dev_act, self._dev_temp, self._dev_topk,
                    self._dev_topp, self._dev_keys)
        if self._draft_model is None:
            return (self.caches, self._dev_tok, self._dev_pos,
                    self._dev_act, self._dev_temp, self._dev_topk,
                    self._dev_topp, self._dev_keys)
        return (self.caches, self.d_caches, self._dev_tok, self._dev_pos,
                self._dev_act, self._dev_temp, self._dev_topk,
                self._dev_topp, self._dev_keys)

    def _prog_args(self):
        """Leading arguments of every prefill program: params (+ draft
        params under speculation) then the device-resident state."""
        if self._draft_model is None:
            return (self.params,) + self._state_args()
        return (self.params, self._draft_params) + self._state_args()

    def _apply_state(self, res):
        """Unpack a prefill program's ``(first, pool[, draft pool],
        [block tables,] *state)`` result, installing the new device
        arrays; returns ``first``."""
        if self.paged:
            if self._draft_model is None:
                (first, self.caches, self._dev_bt, self._dev_tok,
                 self._dev_pos, self._dev_act, self._dev_temp,
                 self._dev_topk, self._dev_topp, self._dev_keys) = res
            else:
                (first, self.caches, self.d_caches, self._dev_bt,
                 self._dev_dbt, self._dev_tok, self._dev_pos,
                 self._dev_act, self._dev_temp, self._dev_topk,
                 self._dev_topp, self._dev_keys) = res
            return first
        if self._draft_model is None:
            (first, self.caches, self._dev_tok, self._dev_pos,
             self._dev_act, self._dev_temp, self._dev_topk,
             self._dev_topp, self._dev_keys) = res
        else:
            (first, self.caches, self.d_caches, self._dev_tok,
             self._dev_pos, self._dev_act, self._dev_temp, self._dev_topk,
             self._dev_topp, self._dev_keys) = res
        return first

    def _sampling_row(self, h: RequestHandle):
        """One request's sampling params as (1,)-shaped device rows for
        the chunk/final programs."""
        return (self._put(np.asarray([h.temperature], np.float32)),
                self._put(np.asarray(
                    [0 if h.top_k is None else int(h.top_k)], np.int32)),
                self._put(np.asarray(
                    [0.0 if h.top_p is None else float(h.top_p)],
                    np.float32)),
                self._put(np.asarray(h.key, np.uint32)[None]))

    # ------------------------------------------------- tenant QoS plumbing
    def register_tenant(self, policy: TenantPolicy) -> None:
        """Install (or replace) one tenant's :class:`TenantPolicy`.
        Thread-safe; takes effect for the next admission.  Requests naming
        no tenant (or an unregistered one) get batch-tier, weight-1,
        unlimited-quota treatment."""
        if not isinstance(policy, TenantPolicy):
            raise ValueError(f"expected a TenantPolicy, got "
                             f"{type(policy).__name__}")
        with self._qlock:
            self._tenants[policy.name] = policy

    def _tenant_stats(self, tenant: str) -> Dict[str, int]:
        """The per-tenant counter dict, created lazily.  Caller holds
        ``_qlock`` (the counters are summed cross-thread by drain/stats
        consumers under the same lock discipline as the globals)."""
        ts = self.stats["tenants"].get(tenant)
        if ts is None:
            ts = {"submitted": 0, "completed": 0, "shed": 0,
                  "quota_refused": 0, "preemptions": 0, "resumes": 0}
            self.stats["tenants"][tenant] = ts
        return ts

    def _tier_of(self, tenant: str) -> str:  # dklint: holds _qlock
        pol = self._tenants.get(tenant)
        return "batch" if pol is None else pol.tier

    def _q_push(self, h: RequestHandle, front: bool = False) -> None:  # dklint: holds _qlock
        """Enqueue under ``_qlock``.  A tenant's first-ever push seeds its
        stride pass at the current minimum among backlogged tenants, so a
        newcomer (or a long-idle returner) can't bank idle time and then
        monopolize admissions."""
        q = self._queues.get(h.tenant)
        if q is None:
            q = self._queues[h.tenant] = []
        if not q:  # (re)joining the backlog: no banked credit
            floor = min((self._wf_pass.get(n, 0.0)
                         for n, qq in self._queues.items() if qq),
                        default=0.0)
            self._wf_pass[h.tenant] = max(
                self._wf_pass.get(h.tenant, 0.0), floor)
        if front:
            q.insert(0, h)
        else:
            q.append(h)
        self._qdepth += 1
        if self._tier_of(h.tenant) == "interactive":
            self._q_int += 1

    def _q_pop_locked(self) -> Optional[RequestHandle]:  # dklint: holds _qlock
        """Weighted-fair pick under ``_qlock``: interactive-tier tenants
        strictly before batch-tier; within a tier, the backlogged tenant
        with the smallest stride pass (pass += 1/weight per pick); within
        a tenant, highest ``priority`` first, FIFO among equals.  With a
        single tenant of uniform priority this degenerates to the plain
        FIFO the pre-QoS engine ran."""
        best_name, best_key = None, None
        for name, q in self._queues.items():
            if not q:
                continue
            lvl = 0 if self._tier_of(name) == "interactive" else 1
            key = (lvl, self._wf_pass.get(name, 0.0), name)
            if best_key is None or key < best_key:
                best_name, best_key = name, key
        if best_name is None:
            return None
        q = self._queues[best_name]
        idx = max(range(len(q)), key=lambda i: (q[i].priority, -i))
        h = q.pop(idx)
        self._qdepth -= 1
        if self._tier_of(best_name) == "interactive":
            self._q_int -= 1
        pol = self._tenants.get(best_name)
        weight = 1.0 if pol is None else pol.weight
        self._wf_pass[best_name] = (self._wf_pass.get(best_name, 0.0)
                                    + 1.0 / weight)
        return h

    def _q_snapshot_locked(self) -> List[RequestHandle]:  # dklint: holds _qlock
        """Every queued handle (all tenants, queue order) under
        ``_qlock``."""
        return [h for q in self._queues.values() for h in q]

    def _q_clear_locked(self) -> List[RequestHandle]:  # dklint: holds _qlock
        out = self._q_snapshot_locked()
        self._queues.clear()
        self._qdepth = 0
        self._q_int = 0
        return out

    # ------------------------------------------------------------ admission
    def submit(self, prompt, num_steps: int, temperature: float = 0.0,
               top_k: Optional[int] = None, top_p: Optional[float] = None,
               eos_id: Optional[int] = None, pad_id: Optional[int] = None,
               seed: int = 0, rng: Optional[jax.Array] = None,
               block: bool = True, timeout: Optional[float] = None,
               deadline_s: Optional[float] = None,
               tenant: Optional[str] = None,
               priority: int = 0) -> RequestHandle:
        """Enqueue one request; returns its :class:`RequestHandle`.

        ``prompt``: (P,) int tokens.  Sampling/stopping knobs mirror
        ``generate`` exactly (that is the bit-identity contract); the
        request's rng is ``rng`` if given, else ``PRNGKey(seed)``.
        Backpressure: with the queue at ``queue_capacity``, ``block=True``
        waits (up to ``timeout``), ``block=False`` raises :class:`QueueFull`
        immediately.  ``deadline_s`` (default: the submitting tenant's
        ``TenantPolicy.deadline_s``, else the engine's
        ``default_deadline_s``) bounds the request's whole lifetime,
        queueing included: an expired request is retired with reason
        ``"deadline"`` — shed before prefill if still queued, mid-run with
        its slot freed immediately if decoding.  Raises :class:`Draining`
        while ``drain`` is in progress and :class:`EngineDead` on a dead
        engine.

        QoS: ``tenant`` names the submitting tenant (default
        ``"default"``) — admission is weighted-fair across backlogged
        tenants per their registered :class:`TenantPolicy`; a tenant over
        its token-bucket quota raises :class:`QuotaExceeded` immediately
        (even with ``block=True`` — quota is policy, not backpressure).
        ``priority`` orders requests WITHIN a tenant's queue (higher
        first); batch-tier running requests may additionally be preempted
        (swapped out, later resumed bit-identically) when the interactive
        tier is starved.
        """
        if self.role == "decode":
            raise ValueError(
                "role='decode' engines admit only shipped block sets "
                "(submit_prefilled) — route plain submissions to the "
                "prefill engine or a DisaggPair")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1:
            raise ValueError(f"prompt must be 1-D tokens, got shape "
                             f"{prompt.shape} — submit one request per row")
        if num_steps < 0:
            raise ValueError(f"num_steps must be >= 0, got {num_steps}")
        tenant = "default" if tenant is None else str(tenant)
        priority = int(priority)
        if deadline_s is None:
            with self._qlock:  # register_tenant may race admission
                pol = self._tenants.get(tenant)
            if pol is not None and pol.deadline_s is not None:
                deadline_s = pol.deadline_s
            else:
                deadline_s = self.default_deadline_s
        elif deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        key = rng if rng is not None else jax.random.PRNGKey(int(seed))
        _validate_sampling(temperature, key, top_k, top_p)
        _validate_stopping(eos_id, pad_id, self._vocab)
        total = len(prompt) + int(num_steps)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if total > self.max_len:
            raise ValueError(f"prompt ({len(prompt)}) + num_steps "
                             f"({num_steps}) = {total} exceeds the engine's "
                             f"max_len {self.max_len}")
        with self._qlock:
            if self._dead is not None:
                raise EngineDead(str(self._dead)) from self._dead
            if self._draining:
                raise Draining("serving engine is draining; admission "
                               "stopped")
            pol = self._tenants.get(tenant)
            if pol is not None and not pol._take(time.monotonic()):
                # policy refusal BEFORE requests_submitted so drain()'s
                # terminal accounting never waits on a refused request;
                # per-tenant so one tenant's refusals don't dilute the
                # global shed_rate (requests_rejected untouched)
                self._tenant_stats(tenant)["quota_refused"] += 1
                self.stats["quota_refused"] += 1
                raise QuotaExceeded(
                    f"tenant {tenant!r} over its token-bucket quota "
                    f"({pol.rate}/s, burst {pol.burst})")
            self._next_id += 1
            handle = RequestHandle(self._next_id, prompt, num_steps,
                                   temperature, top_k, top_p, eos_id,
                                   pad_id, key, deadline_s=deadline_s,
                                   tenant=tenant, priority=priority)
            self.stats["requests_submitted"] += 1
            tstats = self._tenant_stats(tenant)
            tstats["submitted"] += 1
            if num_steps == 0:  # nothing to generate: complete in place
                handle._finish("empty")
                self.stats["requests_completed"] += 1
                tstats["completed"] += 1
                return handle
            while self._qdepth >= self.queue_capacity:
                if not block or not self._not_full.wait(timeout=timeout):
                    self.stats["requests_rejected"] += 1
                    tstats["shed"] += 1
                    raise QueueFull(
                        f"admission queue at capacity "
                        f"({self.queue_capacity}); request {handle.id} shed")
                # _declare_dead / drain notify _not_full while we wait —
                # re-check on every wake or the request lands in a queue no
                # scheduler will ever pop (result() would hang forever).
                # Both raises count as admission sheds (requests_rejected)
                # so the terminal accounting drain() sums stays balanced.
                if self._dead is not None:
                    self.stats["requests_rejected"] += 1
                    raise EngineDead(str(self._dead)) from self._dead
                if self._draining:
                    self.stats["requests_rejected"] += 1
                    raise Draining("serving engine is draining; admission "
                                   "stopped")
            self._q_push(handle)
            self.stats["queue_peak"] = max(self.stats["queue_peak"],
                                           self._qdepth)
            self._have_work.notify()
            qd = self._qdepth
        self._publish_load(qd=qd)
        return handle

    def submit_prefilled(self, blocks, prompt, first_token: int,
                         num_steps: int, temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         eos_id: Optional[int] = None,
                         pad_id: Optional[int] = None,
                         block: bool = True, timeout: Optional[float] = None,
                         deadline_s: Optional[float] = None,
                         tenant: Optional[str] = None,
                         priority: int = 0) -> RequestHandle:
        """Decode-role admission: enqueue a request whose prefill already
        ran elsewhere.  ``blocks`` is the shipped
        :class:`networking.KVBlocks` (prompt KV in logical block order +
        the request's RNG key), ``first_token`` the token the prefill
        engine sampled at the prompt boundary — pushed into the handle
        immediately, so the client-visible stream is unchanged.
        ``num_steps`` counts TOTAL generated tokens, the shipped first one
        included (the unified-engine contract).  The scheduler scatters
        the payload into this engine's OWN arena blocks
        (``_PagedKVPool.admit`` plain allocation — physical ids never
        cross engines) and the slot enters the token loop at the shipped
        position.  Geometry lies (wrong arena shape/dtype for this model)
        raise ``ValueError``; torn/hostile payloads should be rejected by
        ``blocks.validate()`` at the transport boundary BEFORE this call.
        Backpressure/death semantics mirror :meth:`submit` exactly."""
        if self.role != "decode":
            raise ValueError("submit_prefilled needs role='decode' — "
                             f"this engine is role={self.role!r}")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or len(prompt) < 1:
            raise ValueError(f"prompt must be 1-D tokens (>= 1), got "
                             f"shape {prompt.shape}")
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1 (it counts the "
                             f"shipped first token), got {num_steps}")
        tenant = "default" if tenant is None else str(tenant)
        priority = int(priority)
        if deadline_s is None:
            with self._qlock:  # register_tenant may race admission
                pol = self._tenants.get(tenant)
            if pol is not None and pol.deadline_s is not None:
                deadline_s = pol.deadline_s
            else:
                deadline_s = self.default_deadline_s
        elif deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        kvb = blocks
        if kvb.block_size != self.block_size:
            raise ValueError(
                f"shipped blocks are {kvb.block_size}-token, this arena "
                f"pages {self.block_size}-token blocks")
        if kvb.positions != len(prompt):
            raise ValueError(
                f"shipped positions ({kvb.positions}) disagree with the "
                f"prompt length ({len(prompt)})")
        total = len(prompt) + int(num_steps)
        if total > self.max_len:
            raise ValueError(f"prompt ({len(prompt)}) + num_steps "
                             f"({num_steps}) = {total} exceeds the engine's "
                             f"max_len {self.max_len}")
        if len(kvb.layers) != len(self.caches):
            raise ValueError(
                f"shipped payload spans {len(kvb.layers)} layers, this "
                f"model has {len(self.caches)}")
        for i, (c, mine) in enumerate(zip(kvb.layers, self.caches)):
            if (c is None) != (mine is None):
                raise ValueError(f"layer {i} cache presence disagrees "
                                 "with this model")
            if c is None:
                continue
            if ("ks" in c) != ("ks" in mine):
                raise ValueError(
                    f"layer {i} quantization disagrees: shipped "
                    f"{'int8' if 'ks' in c else 'dense'} KV, this arena is "
                    f"{'int8' if 'ks' in mine else 'dense'}")
            if c["k"].shape[1:] != mine["k"].shape[1:] \
                    or c["k"].dtype != mine["k"].dtype:
                raise ValueError(
                    f"layer {i} shipped rows are {c['k'].shape[1:]} "
                    f"{c['k'].dtype}, this arena holds "
                    f"{mine['k'].shape[1:]} {mine['k'].dtype}")
        _validate_stopping(eos_id, pad_id, self._vocab)
        key = np.asarray(kvb.key, np.uint32)
        with self._qlock:
            if self._dead is not None:
                raise EngineDead(str(self._dead)) from self._dead
            if self._draining:
                raise Draining("serving engine is draining; admission "
                               "stopped")
            pol = self._tenants.get(tenant)
            if pol is not None and not pol._take(time.monotonic()):
                self._tenant_stats(tenant)["quota_refused"] += 1
                self.stats["quota_refused"] += 1
                raise QuotaExceeded(
                    f"tenant {tenant!r} over its token-bucket quota "
                    f"({pol.rate}/s, burst {pol.burst})")
            self._next_id += 1
            handle = RequestHandle(self._next_id, prompt, num_steps,
                                   temperature, top_k, top_p, eos_id,
                                   pad_id, key, deadline_s=deadline_s,
                                   tenant=tenant, priority=priority)
            handle.kvblocks = kvb
            self.stats["requests_submitted"] += 1
            tstats = self._tenant_stats(tenant)
            tstats["submitted"] += 1
            # the shipped first token IS this request's first generated
            # token: push it now (TTFT on this engine is the hand-off
            # instant) and complete in place when it already terminates
            handle._push(int(first_token))
            if (eos_id is not None and int(first_token) == int(eos_id)) \
                    or num_steps == 1:
                reason = ("eos" if eos_id is not None
                          and int(first_token) == int(eos_id) else "length")
                handle._finish(reason)
                self.stats["requests_completed"] += 1
                tstats["completed"] += 1
                self.stats["tokens_generated"] += 1
                return handle
            while self._qdepth >= self.queue_capacity:
                if not block or not self._not_full.wait(timeout=timeout):
                    self.stats["requests_rejected"] += 1
                    tstats["shed"] += 1
                    raise QueueFull(
                        f"admission queue at capacity "
                        f"({self.queue_capacity}); request {handle.id} shed")
                if self._dead is not None:
                    self.stats["requests_rejected"] += 1
                    raise EngineDead(str(self._dead)) from self._dead
                if self._draining:
                    self.stats["requests_rejected"] += 1
                    raise Draining("serving engine is draining; admission "
                                   "stopped")
            self.stats["tokens_generated"] += 1
            self._q_push(handle)
            self.stats["queue_peak"] = max(self.stats["queue_peak"],
                                           self._qdepth)
            self._have_work.notify()
            qd = self._qdepth
        self._publish_load(qd=qd)
        return handle

    @property
    def queue_depth(self) -> int:
        with self._qlock:
            return self._qdepth

    @property
    def active_requests(self) -> int:
        return int(self._active.sum())

    # --------------------------------------------------- load snapshot
    def _publish_load(self, qd: Optional[int] = None,
                      draining: Optional[bool] = None,
                      dead: Optional[bool] = None) -> None:
        """Republish the lock-free load snapshot (see ``__init__``).

        Must be called OUTSIDE any ``_qlock`` block: callers that need an
        exact queue depth capture it under the lock and pass it in; a
        ``None`` field carries the previous snapshot's value forward.
        Everything else read here is scheduler-confined (``_free``,
        ``_active``, the trie counter) or an already-synchronised stats
        counter — stale-by-one is fine for routing."""
        prev = self._load_snapshot
        stats = self.stats
        with self._qlock:
            qi = self._q_int
        self._load_snapshot = {
            "queue_depth": prev["queue_depth"] if qd is None else int(qd),
            "slots_free": len(self._free),
            "slots_total": self.num_slots,
            "active": int(self._active.sum()),
            "trie_blocks": (self._pool.trie_nodes if self.paged else 0),
            "queue_capacity": self.queue_capacity,
            "max_len": self.max_len,
            "draining": (prev["draining"] if draining is None
                         else bool(draining)),
            "dead": prev["dead"] if dead is None else bool(dead),
            "prefix_hit_tokens": stats["prefix_hit_tokens"],
            "prefill_tokens": stats["prefill_tokens"],
            "tokens_generated": stats["tokens_generated"],
            "requests_completed": stats["requests_completed"],
            "requests_failed": stats["requests_failed"],
            "queued_interactive": qi,
        }

    def load(self) -> Dict[str, Any]:
        """Cheap read-only load snapshot for routing decisions: queue
        depth, free/total slots, active requests, prefix-trie cached block
        count, draining/dead flags, and a few throughput counters.  Takes
        NO locks (the snapshot dict is republished by reference from the
        scheduler/submit paths), so a router may poll it at any rate
        without perturbing the hot path.  Values may trail the engine by
        one scheduler iteration."""
        return dict(self._load_snapshot)

    def _pop_queued(self) -> Optional[RequestHandle]:
        with self._qlock:
            h = self._q_pop_locked()
            if h is None:
                return None
            self._not_full.notify()
            qd = self._qdepth
        self._publish_load(qd=qd)
        return h

    # ------------------------------------------------- cancel + deadlines
    def cancel(self, handle: RequestHandle) -> bool:
        """Request cancellation (thread-safe, any thread): the scheduler
        retires the request with reason ``"cancel"`` within ONE iteration —
        a queued request is shed before prefill, a running one frees its KV
        slot immediately (the disconnect-reclamation path the wire server
        drives).  Returns False if the request already finished."""
        with handle._cond:
            if handle.finish is not None:
                return False
            if handle.cancelled_at is None:
                handle.cancelled_at = time.perf_counter()
        with self._qlock:
            self._have_work.notify_all()  # prompt reclamation on idle loops
        return True

    def _reap(self) -> bool:
        """Retire cancelled and deadline-expired requests: queued ones are
        shed before ever taking a slot; running ones mid-run, freeing the
        slot for the next queued request.  Runs at the top of every
        scheduler iteration."""
        now = time.perf_counter()
        shed: List[RequestHandle] = []
        with self._qlock:
            for name, q in self._queues.items():
                if not any(h.cancelled_at is not None or h._expired(now)
                           for h in q):
                    continue
                keep: List[RequestHandle] = []
                for h in q:
                    if h.cancelled_at is not None or h._expired(now):
                        shed.append(h)
                        self._qdepth -= 1
                        if self._tier_of(name) == "interactive":
                            self._q_int -= 1
                    else:
                        keep.append(h)
                self._queues[name] = keep
            if shed:
                self._not_full.notify_all()
        for h in shed:
            reason = "cancel" if h.cancelled_at is not None else "deadline"
            if h._finish(reason):
                # held_slot=False: a queued shed never occupied a KV slot,
                # so it must not contribute a (near-zero) sample to the
                # slot_reclaim_ms reclamation-latency metric
                self._account_terminal(h, reason, now, held_slot=False)
                with self._qlock:  # drain()'s busy() sums this cross-thread
                    self.stats["requests_completed"] += 1
                    self._tenant_stats(h.tenant)["completed"] += 1
        did = bool(shed)
        for slot in np.flatnonzero(self._active):
            h = self._handles[slot]
            if h.cancelled_at is not None:
                self._retire(int(slot), "cancel")
                did = True
            elif h._expired(now):
                self._retire(int(slot), "deadline")
                did = True
        for slot in list(self._prefilling):
            h = self._prefilling[slot].handle
            if h.cancelled_at is not None:
                self._abort_prefill(slot, "cancel")
                did = True
            elif h._expired(now):
                self._abort_prefill(slot, "deadline")
                did = True
        # suspended (swapped-out) requests hold no slot or blocks — their
        # cancel/deadline path is pure bookkeeping: drop the host-side
        # swap record and retire the handle (held_slot=False: nothing to
        # reclaim, so no slot_reclaim_ms sample)
        with self._qlock:  # _declare_dead clears _suspended cross-thread
            susp = list(self._suspended.items())
        for rid, rec in susp:
            h = rec.handle
            if h.cancelled_at is not None:
                reason = "cancel"
            elif h._expired(now):
                reason = "deadline"
            else:
                continue
            with self._qlock:
                self._suspended.pop(rid, None)
            if h._finish(reason):
                self._account_terminal(h, reason, now, held_slot=False)
                with self._qlock:
                    self.stats["requests_completed"] += 1
                    self._tenant_stats(h.tenant)["completed"] += 1
            did = True
        return did

    def _abort_prefill(self, slot: int, reason: str) -> None:
        """Retire a request MID-chunked-prefill (cancel / deadline /
        client disconnect): the slot goes straight back to the pool — the
        chunks already written are junk the next occupant's prefill
        overwrites, exactly like a retired decode slot's cache row.
        Paged engines release the job's block plan — refcounts drop and
        its private blocks (mid-chunk contents included) go straight back
        to the allocator; the device table was never installed, so no
        junk write can reach them once reallocated."""
        h = self._prefilling.pop(slot).handle
        self._handles[slot] = None
        self._free.append(slot)
        self._release_blocks(slot)
        if h._finish(reason):
            with self._qlock:  # drain()'s busy() sums this cross-thread
                self.stats["requests_completed"] += 1
                self._tenant_stats(h.tenant)["completed"] += 1
            self._account_terminal(h, reason, time.perf_counter())

    def _release_blocks(self, slot: int) -> None:
        if self._pool is None:
            return
        plan = self._plans.pop(slot, None)
        if plan is not None:
            self._pool.release(plan)

    def _account_terminal(self, h: RequestHandle, reason: str,
                          now: float, held_slot: bool = True) -> None:
        """Reason counters, plus — for requests that actually held a KV
        slot (``held_slot``) — the slot-reclaim latency sample
        (cancel/expiry instant → slot free) for the
        ``serving_slot_reclaim_ms`` bench.  Queue sheds keep their
        cancelled/expired counters but contribute no reclaim sample."""
        if reason == "cancel":
            self.stats["requests_cancelled"] += 1
            if held_slot and h.cancelled_at is not None:
                self.stats["slot_reclaim_ms"].append(
                    round((now - h.cancelled_at) * 1e3, 3))
        elif reason == "deadline":
            self.stats["requests_expired"] += 1
            if held_slot and h.deadline is not None:
                self.stats["slot_reclaim_ms"].append(
                    round((now - h.deadline) * 1e3, 3))

    # ------------------------------------------------------------- prefill
    def _prefill(self, slot: int, h: RequestHandle) -> None:
        """EAGER-mode admission (``prefill_mode="eager"``, the reference
        path): one per-request prompt forward through the same eager
        ``_forward`` offline ``generate`` prefills with — identical
        numerics — first token sampled at ``p_len - 1`` through the shared
        ``sample_logits``, cache row scattered into the pool."""
        p_len = len(h.prompt)
        prompt = jnp.asarray(h.prompt[None], jnp.int32)
        row = init_cache(self.model, 1,
                         p_len if self.rolling else self.max_len)
        logits, row = _forward(self.model, self.params, row, prompt, 0)
        first = sample_logits(logits[:, -1], p_len - 1, h.temperature,
                              h.key, h.top_k, h.top_p)
        if self.rolling:
            ringed = []
            for layer, cache in zip(self.model.layers, row):
                if cache is None:
                    ringed.append(None)
                    continue
                w = layer._mha().attention_window
                ringed.append({name: _to_ring(cache[name], p_len, w)
                               for name in ("k", "v")})
            row = ringed
        self.caches = self._write_slot_fn(self.caches, row,
                                          jnp.int32(slot))
        h.slot = slot
        h.started_at = time.perf_counter()
        self._handles[slot] = h
        self._positions[slot] = p_len
        self._cur_tok[slot] = int(first[0])
        self._active[slot] = True
        self._temp[slot] = h.temperature
        self._topk[slot] = 0 if h.top_k is None else int(h.top_k)
        self._topp[slot] = 0.0 if h.top_p is None else float(h.top_p)
        self._keys[slot] = np.asarray(h.key, np.uint32)
        self.stats["prefills"] += 1
        self.stats["slot_requests"][slot] += 1
        self.stats["prefill_tokens"] += p_len
        self._emit(slot, int(first[0]))

    def _bucket_of(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _schedule_prefills(self) -> bool:
        """Spend up to ``prefills_per_step`` prefill work units this
        iteration: first advance chunked prefills already holding slots
        (one chunk each — finishing started work bounds every occupant's
        TTFT), then admit queued requests.  Bucketed mode gathers short
        prompts into per-bucket batches (one jitted forward each) and
        routes prompts longer than ``prefill_chunk`` to the chunked path;
        eager mode prefills per request, as it always did.

        Paged engines additionally walk the radix index per admission:
        matched prefix blocks are shared (COW at a partial boundary), the
        block chain is reserved from the allocator, and — when blocks
        are exhausted even after evicting cached chains — the head
        request stays queued until retirements free blocks (FIFO
        head-of-line, deliberately: admission order is the fairness
        contract).  Chunked routing keys on the UNMATCHED suffix length,
        so a long shared prompt with a hot prefix admits in one bucket
        program."""
        did = False
        budget = self.prefills_per_step
        if self.paged:
            self._pool.next_epoch()
        if self.role == "decode":
            # disaggregated ingest replaces prefill entirely: each queued
            # handle carries a shipped block set; admission is one plain
            # block allocation + one jitted scatter/install dispatch.
            # Block exhaustion requeues at the FRONT and stops, exactly
            # like the paged prefill path (FIFO fairness contract).
            while budget > 0 and self._free:
                h = self._pop_queued()
                if h is None:
                    break
                if not self._ingest(h):
                    with self._qlock:
                        self._q_push(h, front=True)
                    break
                budget -= 1
                did = True
            return did
        for slot in list(self._prefilling):
            if budget <= 0:
                break
            self._advance_chunk(slot)
            budget -= 1
            did = True
        batch: List[RequestHandle] = []
        plans: Dict[int, _BlockPlan] = {}
        while budget > 0 and len(self._free) > len(batch):
            h = self._pop_queued()
            if h is None:
                break
            plan = None
            if self.paged:
                plan = self._admit_blocks(h)
                if plan is None:
                    # no blocks even after eviction: requeue at the FRONT
                    # and stop admitting — retirements will free blocks.
                    # An interactive-tier request starving on BLOCKS (not
                    # slots) flags the preemption pass: next iteration a
                    # batch-tier victim is swapped out to free its chain
                    with self._qlock:
                        interactive = (self._tier_of(h.tenant)
                                       == "interactive")
                        self._q_push(h, front=True)
                    if interactive:
                        self._int_blocked = True
                    break
            budget -= 1
            did = True
            if self.prefill_mode == "eager":
                self._prefill(self._free.pop(), h)
            elif (len(h.prompt) - (plan.matched if plan else 0)
                    > self.prefill_chunk):
                self._start_chunked(self._free.pop(), h, plan)
            else:
                if plan is not None:
                    plans[h.id] = plan
                    self._pool.publish(plan, h.prompt)
                batch.append(h)
        if batch:
            self._batch_prefill(batch, plans)
        return did

    # ------------------------------------------------- paged admission
    def _admit_blocks(self, h: RequestHandle) -> Optional[_BlockPlan]:
        """Reserve a request's block chain (trie walk + allocation) and
        dispatch its copy-on-write block copy, if any."""
        # a prefill-role engine writes ONLY the prompt's KV (the first
        # sampled token's write happens on the decode engine at position
        # p_len), so its chain stops at ceil(p_len / bs)
        total = len(h.prompt) + (0 if self.role == "prefill"
                                 else h.num_steps)
        if self.rolling:
            plan = self._pool.admit(None, self._blocks_per_slot)
        else:
            # target and draft pools page the same chain, and both write
            # at most up to the verify frontier — positions past `total`
            # drop into the null block via the table, so ceil(total/bs)
            # blocks cover every entry a live query can ever attend
            plan = self._pool.admit(h.prompt,
                                    -(-total // self.block_size))
        if plan is not None and plan.cow is not None:
            src, dst = plan.cow
            if self._draft_model is None:
                self.caches = self._copy_fn(self.caches, src, dst)
            else:
                self.caches, self.d_caches = self._copy_fn(
                    self.caches, self.d_caches, src, dst)
        return plan

    def _row_tables(self, plan: _BlockPlan):
        """A plan's chain as null-padded numpy block-table rows (target
        [+ draft])."""
        bt = np.full((self._t_tbl,), self.kv_blocks, np.int32)
        n = min(len(plan.blocks), self._t_tbl - 1)
        bt[:n] = plan.blocks[:n]
        if self._draft_model is None:
            return bt, None
        dbt = np.full((self._d_tbl,), self.kv_blocks, np.int32)
        n = min(len(plan.blocks), self._d_tbl - 1)
        dbt[:n] = plan.blocks[:n]
        return bt, dbt

    def _ingest(self, h: RequestHandle) -> bool:
        """Admit ONE shipped block set (decode role): allocate this
        engine's own private chain (``admit(None, ...)`` — no trie, so
        release is a plain refund and the zero-leak contract is the
        standard retirement path), scatter the payload into those blocks,
        and install the slot's device row at the shipped position.
        Returns False when blocks are unavailable (the caller requeues at
        the front and waits for retirements)."""
        kvb = h.kvblocks
        bs = self.block_size
        total = len(h.prompt) + h.num_steps
        plan = self._pool.admit(None, -(-total // bs))
        if plan is None:
            return False
        t0 = time.perf_counter()
        slot = self._free.pop()
        h.slot = slot
        h.started_at = t0
        self._handles[slot] = h
        self._plans[slot] = plan
        self.stats["slot_requests"][slot] += 1
        n_src = kvb.num_blocks
        rows = np.full((self._blocks_per_slot,), self.kv_blocks, np.int32)
        rows[:n_src] = plan.blocks[:n_src]
        phys = (rows[:, None] * bs
                + np.arange(bs, dtype=np.int32)[None, :]).reshape(-1)
        pad = (self._blocks_per_slot - n_src) * bs
        payload = []
        for c in kvb.layers:
            if c is None:
                payload.append(None)
                continue
            payload.append({
                k: self._put(np.concatenate(
                    [np.asarray(v),
                     np.zeros((pad,) + v.shape[1:], v.dtype)])
                    if pad else np.ascontiguousarray(v))
                for k, v in c.items()})
        bt, _ = self._row_tables(plan)
        (self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
         self._dev_act, self._dev_temp, self._dev_topk, self._dev_topp,
         self._dev_keys) = self._ingest_fn(
            self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
            self._dev_act, self._dev_temp, self._dev_topk,
            self._dev_topp, self._dev_keys,
            self._put(phys), payload, self._put(np.int32(slot)),
            self._put(bt), self._put(np.int32(h.tokens[0])),
            self._put(np.int32(len(h.prompt))),
            self._put(np.float32(h.temperature)),
            self._put(np.int32(0 if h.top_k is None else h.top_k)),
            self._put(np.float32(0.0 if h.top_p is None else h.top_p)),
            self._put(np.asarray(h.key, np.uint32)))
        self._mirror_admit(slot, h)
        self._cur_tok[slot] = h.tokens[0]
        self.stats["kv_blocks_ingested"] += n_src
        self.stats["kv_block_bytes_ingested"] += kvb.nbytes
        self.stats["transfer_ms"].append(
            (time.perf_counter() - t0) * 1000.0)
        return True

    def _batch_prefill(self, batch: List[RequestHandle],
                       plans: Optional[Dict[int, _BlockPlan]] = None
                       ) -> None:
        """Admit up to ``prefills_per_step`` short prompts in ONE jitted
        batched forward per length bucket.  The program batch is always
        ``prefills_per_step`` rows (one compiled shape per bucket);
        unfilled rows target slot ``num_slots``, so every write they
        produce is dropped on device.  Paged engines bucket by UNMATCHED
        suffix length and pass each row's match frontier + block-table
        row; ``prefill_tokens`` counts only what is actually prefilled
        (the hit tokens live in ``prefix_hit_tokens``)."""
        groups: Dict[int, List[RequestHandle]] = {}
        for h in batch:
            matched = plans[h.id].matched if (plans and h.id in plans) \
                else 0
            groups.setdefault(self._bucket_of(len(h.prompt) - matched),
                              []).append(h)
        for width, group in groups.items():
            nb = self.prefills_per_step
            prompts = np.zeros((nb, width), np.int32)
            match = np.zeros((nb,), np.int32)
            p_lens = np.ones((nb,), np.int32)
            slots = np.full((nb,), self.num_slots, np.int32)
            r_temp = np.zeros((nb,), np.float32)
            r_topk = np.zeros((nb,), np.int32)
            r_topp = np.zeros((nb,), np.float32)
            r_keys = np.zeros((nb, 2), np.uint32)
            if self.paged:
                row_bt = np.full((nb, self._t_tbl), self.kv_blocks,
                                 np.int32)
                row_dbt = (np.full((nb, self._d_tbl), self.kv_blocks,
                                   np.int32)
                           if self._draft_model is not None else None)
            entries: List[Tuple[int, RequestHandle]] = []
            for i, h in enumerate(group):
                slot = self._free.pop()
                p = len(h.prompt)
                m = 0
                if self.paged:
                    plan = plans[h.id]
                    m = plan.matched
                    self._plans[slot] = plan
                    rb, rd = self._row_tables(plan)
                    row_bt[i] = rb
                    if rd is not None:
                        row_dbt[i] = rd
                prompts[i, :p - m] = h.prompt[m:]
                match[i] = m
                p_lens[i] = p
                slots[i] = slot
                r_temp[i] = h.temperature
                r_topk[i] = 0 if h.top_k is None else int(h.top_k)
                r_topp[i] = 0.0 if h.top_p is None else float(h.top_p)
                r_keys[i] = np.asarray(h.key, np.uint32)
                h.slot = slot
                h.started_at = time.perf_counter()
                self._handles[slot] = h
                self._mirror_admit(slot, h)
                self.stats["prefills"] += 1
                self.stats["slot_requests"][slot] += 1
                self.stats["prefill_tokens"] += p - m
                entries.append((slot, h))
            if self.paged:
                extra = [self._put(prompts), self._put(match),
                         self._put(p_lens), self._put(slots),
                         self._put(row_bt)]
                if row_dbt is not None:
                    extra.append(self._put(row_dbt))
                first = self._apply_state(self._bucket_fn(width)(
                    *self._prog_args(), *extra, self._put(r_temp),
                    self._put(r_topk), self._put(r_topp),
                    self._put(r_keys)))
            else:
                first = self._apply_state(self._bucket_fn(width)(
                    *self._prog_args(), self._put(prompts),
                    self._put(p_lens), self._put(slots), self._put(r_temp),
                    self._put(r_topk), self._put(r_topp),
                    self._put(r_keys)))
            self.stats["prefill_batches"] += 1
            self.stats["prefill_batched_requests"] += len(group)
            self.stats["prefill_batch_size_mean"] = round(
                self.stats["prefill_batched_requests"]
                / self.stats["prefill_batches"], 3)
            self._pending.append(("prefill", first, entries))

    def _start_chunked(self, slot: int, h: RequestHandle,
                       plan: Optional[_BlockPlan] = None) -> None:
        """Claim ``slot`` for a long prompt and run its first chunk; the
        scheduler advances one more chunk per iteration (``_reap`` can
        retire it mid-prefill).  Paged non-rolling jobs skip the staging
        cache entirely — chunks write into the request's own blocks
        (private until the final chunk installs the device table and
        publishes the prompt chain into the trie), starting at the
        matched frontier so a hot shared prefix skips its chunks."""
        h.slot = slot
        h.started_at = time.perf_counter()
        self._handles[slot] = h
        if self.paged:
            self._plans[slot] = plan
            bt, dbt = self._row_tables(plan)
            bt_d = self._put(bt[None])
            dbt_d = self._put(dbt[None]) if dbt is not None else None
            if self.rolling:
                staging = init_cache(self.model, 1, self.max_len)
                d_staging = (init_cache(self._draft_model, 1, self.max_len)
                             if self._draft_model is not None else None)
                job = _PrefillJob(h, staging, d_staging, bt_d, dbt_d)
            else:
                job = _PrefillJob(h, bt=bt_d, dbt=dbt_d)
                job.written = plan.matched
        else:
            staging = init_cache(self.model, 1, self.max_len)
            d_staging = (init_cache(self._draft_model, 1, self.max_len)
                         if self._draft_model is not None else None)
            job = _PrefillJob(h, staging, d_staging)
        self._prefilling[slot] = job
        self.stats["prefills"] += 1
        self.stats["slot_requests"][slot] += 1
        self._advance_chunk(slot)

    def _advance_chunk(self, slot: int) -> None:
        """One chunk of one prefilling slot: write ``prefill_chunk`` more
        prompt tokens into the cache (the final chunk rounds up to a
        length bucket instead, samples the first token, and activates the
        slot for decode)."""
        job = self._prefilling[slot]
        h = job.handle
        p_len = len(h.prompt)
        remaining = p_len - job.written
        offset = job.written
        if remaining > self._chunk_width:
            width, real, final = self._chunk_width, self._chunk_width, False
        else:
            width, real, final = self._bucket_of(remaining), remaining, True
        toks = np.zeros((1, width), np.int32)
        toks[0, :real] = h.prompt[offset:offset + real]
        toks_d = self._put(toks)
        self.stats["prefill_chunks"] += 1
        self.stats["prefill_tokens"] += real
        paged_direct = self.paged and not self.rolling
        if paged_direct:
            off_vec = self._put(np.asarray([offset], np.int32))
            plen_vec = self._put(np.asarray([p_len], np.int32))
        if not final:
            if paged_direct:
                if self._draft_model is not None:
                    self.caches, self.d_caches = self._stage_fn(width)(
                        self.params, self._draft_params, self.caches,
                        self.d_caches, toks_d, off_vec, plen_vec,
                        job.bt, job.dbt)
                else:
                    self.caches = self._stage_fn(width)(
                        self.params, self.caches, toks_d, off_vec,
                        plen_vec, job.bt)
            elif self._draft_model is not None:
                job.staging, job.d_staging = self._stage_fn(width)(
                    self.params, self._draft_params, job.staging,
                    job.d_staging, toks_d, offset)
            else:
                job.staging = self._stage_fn(width)(
                    self.params, job.staging, toks_d, offset)
        else:
            if paged_direct:
                if self._draft_model is not None:
                    first = self._apply_state(self._final_fn(width)(
                        *self._prog_args(), toks_d, slot, off_vec,
                        plen_vec, real - 1, job.bt, job.dbt,
                        *self._sampling_row(h)))
                else:
                    first = self._apply_state(self._final_fn(width)(
                        *self._prog_args(), toks_d, slot, off_vec,
                        plen_vec, real - 1, job.bt,
                        *self._sampling_row(h)))
            elif self.paged:  # rolling: staged chunks, block-table commit
                if self._draft_model is not None:
                    first = self._apply_state(self._final_fn(width)(
                        *self._prog_args(), job.staging, job.d_staging,
                        toks_d, slot, offset, real - 1, p_len,
                        job.bt, job.dbt, *self._sampling_row(h)))
                else:
                    first = self._apply_state(self._final_fn(width)(
                        *self._prog_args(), job.staging, toks_d, slot,
                        offset, real - 1, p_len, job.bt,
                        *self._sampling_row(h)))
            elif self._draft_model is not None:
                first = self._apply_state(self._final_fn(width)(
                    *self._prog_args(), job.staging, job.d_staging,
                    toks_d, slot, offset, real - 1, p_len,
                    *self._sampling_row(h)))
            else:
                first = self._apply_state(self._final_fn(width)(
                    *self._prog_args(), job.staging, toks_d,
                    slot, offset, real - 1, p_len, *self._sampling_row(h)))
            job.staging = None
            job.d_staging = None
            if self.paged:
                # the chain's contents are now fully dispatched: publish
                # the prompt's full blocks into the prefix trie
                self._pool.publish(self._plans[slot], h.prompt)
        job.written += real
        if final:
            del self._prefilling[slot]
            self._mirror_admit(slot, h)
            self._pending.append(("prefill", first, [(slot, h)]))

    def _mirror_admit(self, slot: int, h: RequestHandle) -> None:
        """Host mirrors of the per-slot state the prefill program just set
        on device — the scheduler's bookkeeping view (``_cur_tok`` lands
        when the first token is drained)."""
        self._active[slot] = True
        self._positions[slot] = len(h.prompt)
        self._temp[slot] = h.temperature
        self._topk[slot] = 0 if h.top_k is None else int(h.top_k)
        self._topp[slot] = 0.0 if h.top_p is None else float(h.top_p)
        self._keys[slot] = np.asarray(h.key, np.uint32)

    def _finish_prefilled(self, slot: int, token: int) -> None:
        """Prefill role's hand-off: the drained first token means this
        request's prompt KV is fully written, so gather its blocks out of
        the arena (read-only — shared prefix blocks gather safely), hang
        a :class:`networking.KVBlocks` on the handle, push the token, and
        retire ``"prefilled"`` through the STANDARD path — blocks release
        via ``_release_blocks`` exactly like any retirement, so the
        zero-leak contract holds without a special case."""
        h = self._handles[slot]
        t0 = time.perf_counter()
        plan = self._plans[slot]
        bs = self.block_size
        p_len = len(h.prompt)
        n_src = -(-p_len // bs)
        rows = np.full((self._blocks_per_slot,), self.kv_blocks, np.int32)
        rows[:n_src] = plan.blocks[:n_src]
        phys = (rows[:, None] * bs
                + np.arange(bs, dtype=np.int32)[None, :]).reshape(-1)
        dev = self._gather_fn(self.caches, self._put(phys))
        keep = n_src * bs
        layers = [None if c is None else
                  {k: np.ascontiguousarray(self._fetch(v)[:keep])
                   for k, v in c.items()}
                  for c in dev]
        h.kvblocks = networking.KVBlocks(
            layers, bs, n_src, p_len, np.asarray(h.key, np.uint32))
        h._push(token)
        self.stats["tokens_generated"] += 1
        self.stats["kv_blocks_shipped"] += n_src
        self.stats["kv_block_bytes_shipped"] += h.kvblocks.nbytes
        self.stats["transfer_ms"].append(
            (time.perf_counter() - t0) * 1000.0)
        self._retire(slot, "prefilled")

    # ---------------------------------------------------------- retirement
    def _emit(self, slot: int, token: int) -> None:
        """Record one produced token for the request in ``slot``; retire on
        eos (the eos itself is emitted, as in ``generate``) or length."""
        h = self._handles[slot]
        h._push(token)
        self.stats["tokens_generated"] += 1
        if h.eos_id is not None and token == h.eos_id:
            self._retire(slot, "eos")
        elif len(h.tokens) >= h.num_steps:
            self._retire(slot, "length")

    def _retire(self, slot: int, reason: str) -> None:
        h = self._handles[slot]
        self._handles[slot] = None
        self._active[slot] = False
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 0.0
        self._positions[slot] = 0
        self._cur_tok[slot] = 0
        self._free.append(slot)
        if self.prefill_mode == "bucketed":
            # deactivate the device row too: an in-flight lookahead step
            # may compute one junk token for it (drained entries skip
            # finished handles), but from the next dispatch on the slot is
            # inert until a prefill program rewrites it.  Paged: the
            # block-table row is re-nulled IN THE SAME program, so that
            # junk (and every later idle pass) drops into the null block
            # while the released blocks go back to the allocator — the
            # one in-flight lookahead write ordered before any program
            # that could reuse them
            if self.paged:
                if self._draft_model is None:
                    self._dev_act, self._dev_bt = self._deact_fn(
                        self._dev_act, self._dev_bt, slot)
                else:
                    (self._dev_act, self._dev_bt,
                     self._dev_dbt) = self._deact_fn(
                        self._dev_act, self._dev_bt, self._dev_dbt, slot)
                self._release_blocks(slot)
            else:
                self._dev_act = self._deact_fn(self._dev_act, slot)
        if h._finish(reason):  # no-op when _declare_dead already failed it
            with self._qlock:  # drain()'s busy() sums this cross-thread
                self.stats["requests_completed"] += 1
                self._tenant_stats(h.tenant)["completed"] += 1
            self._account_terminal(h, reason, time.perf_counter())

    # ----------------------------------------------- preemption (QoS swap)
    def preempt(self, handle: RequestHandle) -> bool:
        """Mark a RUNNING request for preemption (thread-safe): within one
        scheduler iteration its live KV blocks are gathered to host
        memory, its slot and blocks are freed, and it waits in the
        suspended set until capacity is free again — then resumes through
        the jitted ingest program with a bit-identical token stream.
        The deterministic-control surface tests and operators use; the
        scheduler fires the same path itself when the interactive tier is
        starved.  Returns False when the request already finished or this
        engine cannot preempt (needs ``paged=True``, bucketed prefill,
        ``role="unified"``, no rolling window, no speculation)."""
        if not self._can_preempt:
            return False
        with handle._cond:
            if handle.finish is not None:
                return False
        with self._qlock:
            self._preempt_ids.add(handle.id)
            self._have_work.notify_all()
        return True

    def _ensure_swap_fns(self) -> None:
        """Build (lazily) the swap-out gather and swap-in ingest programs.
        Both reuse the disaggregation machinery — ``gather_slot_state``
        wraps the prefill role's block gather and ``_build_ingest_fn`` is
        exactly the decode role's install program — so a preemption
        round-trips bytes through the very path PR 16 ships them over the
        wire with."""
        if self._swap_gather_fn is None:
            self._swap_gather_fn = jax.jit(_dec.gather_slot_state)
        if self._swap_ingest_fn is None:
            self._swap_ingest_fn = self._build_ingest_fn()

    def _suspend_slot(self, slot: int) -> bool:
        """Swap one running request out: flush the decode lookahead (so
        the handle's emitted tokens reach the true frontier), gather its
        live KV blocks + device frontier in one jitted dispatch, copy
        them to host memory, then free the slot and blocks through the
        standard deactivation path — WITHOUT making the handle terminal.
        The d2h fetches land in the PR 9 transfer counters like any
        extraction.  Returns False when the request retired during the
        flush (nothing left to suspend)."""
        h = self._handles[slot]
        if h is None:
            return False
        t0 = time.perf_counter()
        if self._pending:
            self._drain_pending(flush=True)
        if self._handles[slot] is not h or h.finish is not None:
            return False  # eos/length/cancel landed in the flush
        self._ensure_swap_fns()
        plan = self._plans[slot]
        bs = self.block_size
        n_src = max(-(-int(self._positions[slot]) // bs), 1)
        rows = np.full((self._blocks_per_slot,), self.kv_blocks, np.int32)
        rows[:n_src] = plan.blocks[:n_src]
        phys = (rows[:, None] * bs
                + np.arange(bs, dtype=np.int32)[None, :]).reshape(-1)
        dev, d_tok, d_pos, _ = self._swap_gather_fn(
            self.caches, self._put(phys), self._dev_tok, self._dev_pos,
            self._dev_keys, self._put(np.int32(slot)))
        keep = n_src * bs
        layers = [None if c is None else
                  {k: np.ascontiguousarray(self._fetch(v)[:keep])
                   for k, v in c.items()}
                  for c in dev]
        pos, tok = int(self._fetch(d_pos)), int(self._fetch(d_tok))
        rec = _SuspendedReq(h, layers, n_src, pos, tok)
        # free the slot + blocks exactly like _retire, minus the terminal
        # transition: the handle stays live, parked in _suspended
        self._handles[slot] = None
        self._active[slot] = False
        self._temp[slot] = 0.0
        self._topk[slot] = 0
        self._topp[slot] = 0.0
        self._positions[slot] = 0
        self._cur_tok[slot] = 0
        self._free.append(slot)
        if self._draft_model is None:
            self._dev_act, self._dev_bt = self._deact_fn(
                self._dev_act, self._dev_bt, slot)
        else:
            (self._dev_act, self._dev_bt, self._dev_dbt) = self._deact_fn(
                self._dev_act, self._dev_bt, self._dev_dbt, slot)
        self._release_blocks(slot)
        h.slot = None
        nbytes = sum(a.nbytes for c in layers if c is not None
                     for a in c.values())
        with self._qlock:  # _declare_dead drains _suspended cross-thread
            self._suspended[h.id] = rec
            self.stats["preemptions"] += 1
            self._tenant_stats(h.tenant)["preemptions"] += 1
        self.stats["kv_blocks_swapped_out"] += n_src
        self.stats["kv_block_bytes_swapped_out"] += nbytes
        self.stats["preempt_swap_ms"].append(
            (time.perf_counter() - t0) * 1000.0)
        return True

    def _resume_suspended(self, rec: _SuspendedReq) -> bool:
        """Swap one suspended request back in: allocate a fresh private
        chain, scatter the host payload into it, and re-install the
        slot's device row at the SUSPENDED frontier — original RNG key,
        current token, position — through the jitted ingest program.
        Sampling keys fold per (key, absolute position), so the resumed
        stream is bit-identical to the run that was never preempted.
        Returns False when blocks or slots are unavailable (the caller
        retries next iteration)."""
        h = rec.handle
        if not self._free:
            return False
        bs = self.block_size
        total = len(h.prompt) + h.num_steps
        plan = self._pool.admit(None, -(-total // bs))
        if plan is None:
            return False
        t0 = time.perf_counter()
        self._ensure_swap_fns()
        slot = self._free.pop()
        h.slot = slot
        self._handles[slot] = h
        self._plans[slot] = plan
        self.stats["slot_requests"][slot] += 1
        n_src = rec.n_blocks
        rows = np.full((self._blocks_per_slot,), self.kv_blocks, np.int32)
        rows[:n_src] = plan.blocks[:n_src]
        phys = (rows[:, None] * bs
                + np.arange(bs, dtype=np.int32)[None, :]).reshape(-1)
        pad = (self._blocks_per_slot - n_src) * bs
        payload = []
        nbytes = 0
        for c in rec.layers:
            if c is None:
                payload.append(None)
                continue
            nbytes += sum(a.nbytes for a in c.values())
            payload.append({
                k: self._put(np.concatenate(
                    [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                    if pad else v)
                for k, v in c.items()})
        bt, _ = self._row_tables(plan)
        (self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
         self._dev_act, self._dev_temp, self._dev_topk, self._dev_topp,
         self._dev_keys) = self._swap_ingest_fn(
            self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
            self._dev_act, self._dev_temp, self._dev_topk,
            self._dev_topp, self._dev_keys,
            self._put(phys), payload, self._put(np.int32(slot)),
            self._put(bt), self._put(np.int32(rec.tok)),
            self._put(np.int32(rec.pos)),
            self._put(np.float32(h.temperature)),
            self._put(np.int32(0 if h.top_k is None else h.top_k)),
            self._put(np.float32(0.0 if h.top_p is None else h.top_p)),
            self._put(np.asarray(h.key, np.uint32)))
        self._mirror_admit(slot, h)
        self._positions[slot] = rec.pos   # the suspended frontier, not
        self._cur_tok[slot] = rec.tok     # the prompt boundary
        with self._qlock:
            self.stats["resumes"] += 1
            self._tenant_stats(h.tenant)["resumes"] += 1
        self.stats["kv_blocks_resumed"] += n_src
        self.stats["kv_block_bytes_resumed"] += nbytes
        self.stats["preempt_resume_ms"].append(
            (time.perf_counter() - t0) * 1000.0)
        return True

    def _balance_qos(self) -> bool:
        """The preemption scheduler pass (between ``_reap`` and
        ``_schedule_prefills``): suspend explicitly-marked requests and —
        when the interactive tier is starved of slots or blocks — the
        lowest-priority, youngest batch-tier running request (one victim
        per iteration: preemption is expensive; starvation that persists
        keeps firing it); then resume suspended requests oldest-first
        whenever capacity is free and no interactive request is waiting
        (suspended requests outrank the queue — they hold paid-for
        progress)."""
        did = False
        suspended_now = set()
        with self._qlock:
            explicit = set(self._preempt_ids)
            self._preempt_ids.clear()
            starved = self._q_int > 0
        blocked = self._int_blocked
        self._int_blocked = False
        if explicit:
            for slot in np.flatnonzero(self._active):
                h = self._handles[int(slot)]
                if h is not None and h.id in explicit:
                    if self._suspend_slot(int(slot)):
                        suspended_now.add(h.id)
                        did = True
        if starved and (not self._free or blocked):
            victims = []
            for slot in np.flatnonzero(self._active):
                h = self._handles[int(slot)]
                if h is None:
                    continue
                with self._qlock:
                    tier = self._tier_of(h.tenant)
                if tier == "interactive":
                    continue
                victims.append((h.priority, -(h.started_at or 0.0),
                                int(slot)))
            if victims:
                victims.sort()
                v = self._handles[victims[0][2]]
                if self._suspend_slot(victims[0][2]):
                    suspended_now.add(v.id)
                    did = True
        with self._qlock:  # _declare_dead drains _suspended cross-thread
            waiting_int = self._q_int > 0
            susp = list(self._suspended.items())
        if susp and self._free and not waiting_int:
            for rid, rec in susp:
                if not self._free:
                    break
                if rid in suspended_now:
                    # never round-trip a request suspended THIS pass:
                    # the capacity it freed must first be offered to
                    # whatever starved it (admitted one stage later,
                    # in _schedule_prefills)
                    continue
                if rec.handle.finish is not None:
                    with self._qlock:
                        self._suspended.pop(rid, None)  # failed meanwhile
                    continue
                if not self._resume_suspended(rec):
                    break  # block-starved: wait for retirements
                with self._qlock:
                    self._suspended.pop(rid, None)
                did = True
        return did

    # ------------------------------------------------------------ schedule
    def step(self) -> bool:
        """One engine iteration: retire cancelled/expired requests
        (``_reap`` — queued ones shed before prefill, running AND
        mid-chunked-prefill ones freeing their slot mid-run), spend up to
        ``prefills_per_step`` prefill work units (chunk advances + new
        admissions), dispatch one decode step for every running request,
        then drain the pipeline's oldest in-flight step (one-step
        lookahead: the device computes step t+1 while the host emits step
        t's tokens).  Returns whether any work happened.

        Hot weight reload fires only when ``decode_steps`` actually
        ADVANCED onto a multiple of the reload cadence — a reap- or
        prefill-only iteration leaves the counter parked and must not
        re-pull on every pass."""
        self.last_beat = time.monotonic()
        steps_before = self.stats["decode_steps"]
        did = self._reap()
        if self._can_preempt:
            with self._qlock:
                qos_work = bool(self._preempt_ids or self._suspended
                                or self._q_int)
            if qos_work or self._int_blocked:
                did = self._balance_qos() or did
        did = self._schedule_prefills() or did
        if self.role == "prefill":
            # no token loop at all: drain every dispatched prefill NOW
            # (the drained first token triggers extraction + hand-off —
            # with decode gated off, nothing else would ever push a
            # lookahead entry out of the pipeline)
            if self._pending:
                did = self._drain_pending(flush=True) or did
            self._publish_load()
            return did
        if self._active.any():
            self._decode_once()
            did = True
        if self._pending:
            did = self._drain_pending(flush=not self._active.any()) or did
        if (self._reload_every
                and self.stats["decode_steps"] > steps_before
                and self.stats["decode_steps"] % self._reload_every == 0):
            self._pull_weights()
        self._publish_load()
        return did

    def _decode_once(self) -> None:
        if self.prefill_mode == "eager":
            nxt, self.caches = self._step_fn(
                self.params, self.caches, jnp.asarray(self._cur_tok),
                jnp.asarray(self._positions), jnp.asarray(self._active),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._keys))
            nxt = np.asarray(nxt)
            self.stats["decode_steps"] += 1
            self.stats["active_slot_steps"] += int(self._active.sum())
            for slot in np.flatnonzero(self._active):
                self._positions[slot] += 1
                self._cur_tok[slot] = nxt[slot]
                self._emit(int(slot), int(nxt[slot]))
            return
        # bucketed: dispatch only — every argument is already a device
        # array (zero uploads), and the sampled row is fetched one
        # iteration later by _drain_pending (one-step lookahead)
        entries = [(int(s), self._handles[s])
                   for s in np.flatnonzero(self._active)]
        if self._draft_model is not None:
            # speculative round: k draft steps + one batched verify in ONE
            # program; rows commit 1..spec_len+1 tokens each, packed with
            # their per-row counts into the one drained array
            (out, self.caches, self.d_caches, self._dev_tok,
             self._dev_pos) = self._spec_fn(
                self.params, self._draft_params, *self._state_args())
            self.stats["decode_steps"] += 1
            self.stats["verify_calls"] += 1
            self.stats["target_calls"] += 1
            self.stats["drafted"] += self.spec_len * len(entries)
            self.stats["active_slot_steps"] += len(entries)
            self._pending.append(("spec", out, entries))
            return
        out, self.caches, self._dev_pos = self._decode_fn(
            self.params, *self._state_args())
        self._dev_tok = out
        self.stats["decode_steps"] += 1
        self.stats["active_slot_steps"] += len(entries)
        self._pending.append(("decode", out, entries))

    def _drain_pending(self, flush: bool = False) -> bool:
        """Emit the tokens of in-flight steps older than the lookahead
        window (``flush=True`` empties the pipeline — the no-decode-work
        tail).  Each drained entry costs exactly one device→host fetch.
        A slot whose request retired (or was recycled) after dispatch is
        skipped: the lookahead step computed one junk token for it, which
        dies here."""
        did = False
        keep = 0 if flush else self._lookahead
        while len(self._pending) > keep:
            kind, arr, entries = self._pending.popleft()
            vals = self._fetch(arr)
            for i, (slot, h) in enumerate(entries):
                if h.finish is not None or self._handles[slot] is not h:
                    continue
                if kind == "spec":
                    # row ``slot`` committed n tokens this round (its
                    # per-row accept length + 1); emit in order, stopping
                    # the moment eos/length retires the request — the
                    # round's trailing tokens die here, like any
                    # lookahead junk
                    n = int(vals[slot, -1])
                    self.stats["accepted"] += max(n - 1, 0)
                    self._positions[slot] += n
                    for j in range(n):
                        token = int(vals[slot, j])
                        self._cur_tok[slot] = token
                        self._emit(slot, token)
                        if (h.finish is not None
                                or self._handles[slot] is not h):
                            break
                    continue
                token = int(vals[slot] if kind == "decode" else vals[i])
                if kind == "decode":
                    self._positions[slot] += 1
                self._cur_tok[slot] = token
                if self.role == "prefill":
                    self._finish_prefilled(slot, token)
                else:
                    self._emit(slot, token)
            did = True
        return did

    def run_until_idle(self, max_steps: Optional[int] = None) -> None:
        """Drive the scheduler inline until queue and slots are empty (the
        synchronous mode tests and closed-loop benches use).  A crash
        inside a step fails every in-flight handle with
        :class:`EngineDead` before re-raising — waiters on other threads
        never hang on a dead inline engine."""
        steps = 0
        while True:
            try:
                if not self.step():
                    return
            except Exception as e:
                self._declare_dead(e)
                raise
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine still busy after {max_steps} steps "
                    f"(queue={self.queue_depth}, "
                    f"active={self.active_requests})")

    @property
    def slot_occupancy(self) -> Optional[float]:
        """Mean fraction of slots doing useful work per decode step — the
        continuous-batching health metric (1.0 = every step fully packed)."""
        if not self.stats["decode_steps"]:
            return None
        return (self.stats["active_slot_steps"]
                / (self.stats["decode_steps"] * self.num_slots))

    # ------------------------------------------------------- thread driver
    def start(self) -> "ServingEngine":
        """Run the scheduler on a background thread (the wire server's
        mode); idles on the work condition when nothing is queued/active."""
        if self._thread is not None:
            return self
        with self._qlock:
            self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dkt-serving-engine")
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        """Stop the background scheduler thread.

        A decode thread that outlives ``join_timeout`` is wedged inside a
        decode step (stuck compile, hung device transfer): it is logged,
        every in-flight handle is failed with :class:`EngineDead` (so no
        ``result()`` waiter blocks on a thread that will never answer),
        and the thread is detached — the same leak contract as
        ``SocketParameterServer.stop(join_timeout)``."""
        with self._qlock:
            self._running = False
            self._have_work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            if self._thread.is_alive():
                logger.error(
                    "serving engine decode thread still alive after "
                    "stop(join_timeout=%.1fs) — wedged in a decode step; "
                    "failing in-flight requests and detaching the thread",
                    join_timeout)
                self._declare_dead(EngineDead(
                    f"decode thread wedged: did not exit within "
                    f"stop(join_timeout={join_timeout})"))
            self._thread = None
        if self._reload_sock is not None:
            try:
                networking.send_opcode(self._reload_sock, b"q")
                self._reload_sock.close()
            except OSError:
                pass
            self._reload_sock = None
        if self._reload_client is not None:
            try:
                self._reload_client.disconnect()
            except (OSError, ConnectionError):
                pass
            self._reload_client = None

    def drain(self, timeout: Optional[float] = None,
              poll: float = 0.01) -> bool:
        """Graceful drain: stop admission (``submit`` raises
        :class:`Draining`), let every queued and running request finish,
        then stop the scheduler.  Returns True when everything finished
        within ``timeout`` (None = wait forever).  On timeout the
        remaining in-flight handles are failed with :class:`EngineDead`
        (reason ``"drain"``) so no waiter hangs, and False is returned.
        Engines never ``start()``-ed are driven to idle inline by this
        call."""
        with self._qlock:
            self._draining = True
            self._not_full.notify_all()  # blocked submitters raise Draining
        self._publish_load(draining=True)
        t0 = time.monotonic()

        def busy() -> bool:
            # terminal accounting, not queue+active snapshots: a request
            # between queue-pop and slot activation (mid-prefill) is in
            # neither, but it has not reached a terminal state either.
            # rejected requests ARE terminal (incremented before the
            # QueueFull/EngineDead/Draining raise) — without them a single
            # backpressure shed would leave busy() True forever
            with self._qlock:
                s = self.stats
                return (s["requests_submitted"]
                        > s["requests_completed"] + s["requests_failed"]
                        + s["requests_rejected"])

        def timed_out() -> bool:
            return (timeout is not None
                    and time.monotonic() - t0 > timeout)

        if self._thread is None and self._dead is None:
            while busy() and not timed_out():
                try:
                    self.step()
                except Exception as e:
                    self._declare_dead(e)
                    raise
        else:
            while busy() and self._dead is None and not timed_out():
                time.sleep(poll)
        clean = self._dead is None and not busy()
        if not clean and self._dead is None:
            # declare BEFORE stop so waiters unblock immediately with
            # reason "drain" (stop would otherwise block a full
            # join_timeout on a wedged loop first)
            self._declare_dead(
                EngineDead(f"drain timed out after {timeout}s with work "
                           f"in flight"), reason="drain")
        self.stop(join_timeout=10.0 if clean else 2.0)
        if not clean:
            self._fail_stragglers(reason="drain")
        return clean

    def _fail_stragglers(self, reason: str) -> None:
        """Post-join sweep for the declare→exit window: a request the
        scheduler popped from the queue BEFORE ``_declare_dead`` swept it
        can land in ``_handles`` (or ``_suspended``) during the loop's
        final iteration, AFTER the sweep — invisible to both.  With the
        loop joined, fail whatever it left live so no waiter hangs."""
        exc = self._dead
        if exc is None:
            return
        with self._qlock:
            suspended = [rec.handle for rec in self._suspended.values()]
            self._suspended.clear()
        for h in suspended:
            if h._fail(EngineDead(
                    f"request was swapped out (preempted) and not resumed "
                    f"before engine shutdown: {exc}"), reason=reason):
                with self._qlock:
                    self.stats["requests_failed"] += 1
                    self._tenant_stats(h.tenant)["completed"] += 1
        for h in list(self._handles):
            if h is not None and h._fail(EngineDead(str(exc)),
                                         reason=reason):
                with self._qlock:
                    self.stats["requests_failed"] += 1

    # -------------------------------------------------- failure semantics
    def declare_dead(self, reason: str) -> None:
        """Supervisor-facing: mark the engine dead and fail every in-flight
        handle with a typed :class:`EngineDead` (``EngineSupervisor`` calls
        this on a stale heartbeat — a wedged decode step — before
        restarting from ``respawn_clone``)."""
        self._declare_dead(EngineDead(reason))

    def _declare_dead(self, cause: BaseException,
                      reason: str = "error") -> None:
        """Terminal engine failure: stop the loop, shed the queue, and fail
        every queued + running handle so no ``result()``/``next_chunk``
        waiter hangs.  Idempotent (first cause wins).  Slot arrays are NOT
        recycled — a wedged decode thread may still be writing them; a
        restart goes through ``respawn_clone`` (fresh pool) instead."""
        exc = (cause if isinstance(cause, EngineDead)
               else EngineDead(f"serving engine died: {cause!r}"))
        if exc is not cause:
            exc.__cause__ = cause
        with self._qlock:
            self._running = False
            if self._dead is not None:
                return
            self._dead = exc
            queued = self._q_clear_locked()
            suspended = [rec.handle for rec in self._suspended.values()]
            self._suspended.clear()
            self._not_full.notify_all()
            self._have_work.notify_all()
        # suspended requests hold no slot and no blocks — they are invisible
        # to _handles and to busy()'s terminal accounting until failed here;
        # without this, drain()/scale_down() would hang on a swapped-out
        # request forever (its waiter never reaches a terminal state)
        for h in suspended:
            if h._fail(EngineDead(
                    f"request was swapped out (preempted) and not resumed "
                    f"before engine shutdown: {exc}"), reason=reason):
                with self._qlock:
                    self.stats["requests_failed"] += 1
                    self._tenant_stats(h.tenant)["completed"] += 1
        inflight = queued + [h for h in self._handles if h is not None]
        for h in inflight:
            # _handles is read without the scheduler's lock: a still-running
            # decode thread may retire a handle concurrently, making _fail a
            # no-op — only a true transition counts (a request must never
            # land in both requests_completed and requests_failed)
            if h._fail(EngineDead(str(exc)), reason=reason):
                with self._qlock:  # drain()'s busy() sums this cross-thread
                    self.stats["requests_failed"] += 1
        self._publish_load(qd=0, dead=True)

    @property
    def dead(self) -> Optional[BaseException]:
        """The :class:`EngineDead` that killed this engine, or None."""
        return self._dead

    def respawn_clone(self) -> "ServingEngine":
        """A fresh engine over the same model/params and knobs — new KV
        slot pool, empty queue, fresh stats (the ``EngineSupervisor``
        restart path; mirrors ``SocketParameterServer.respawn_clone``)."""
        with self._qlock:  # register_tenant may race a supervisor respawn
            # QoS policy carries over with FRESH token buckets — banked
            # quota credit belongs to the dead engine's admission history
            tenant_pols = [p.clone() for p in self._tenants.values()]
        eng = ServingEngine(
            (self.model, self.params), num_slots=self.num_slots,
            max_len=self.max_len, queue_capacity=self.queue_capacity,
            prefills_per_step=self.prefills_per_step, rolling=self.rolling,
            default_deadline_s=self.default_deadline_s,
            prefill_mode=self.prefill_mode,
            prefill_chunk=self.prefill_chunk,
            spec_draft=(None if self._draft_model is None
                        else (self._draft_model, self._draft_params)),
            spec_len=self.spec_len, quantize=self.quantize,
            kv_dtype=self.kv_dtype,
            # paged knobs carry over with the SAME arena shape but a
            # FRESH trie + allocator — cached prefix chains belong to the
            # dead pool's arena contents, which the clone does not share
            paged=self.paged, block_size=self.block_size,
            kv_blocks=self.kv_blocks, role=self.role,
            tenants=tenant_pols or None)
        # quantized clones re-quantize idempotently; the f32 skeleton the
        # hot-reload path maps pulled weights onto carries over as-is
        # (the clone's params are already quantized, so it could not
        # rebuild the pre-quant dtypes itself)
        if self._fp_skel is not None:
            eng._fp_skel = self._fp_skel
        if self._ps_addr is not None:
            eng.attach_ps(*self._ps_addr, every=self._reload_every,
                          retry_policy=self._reload_policy,
                          shard_plan=self._ps_shard_plan,
                          shard_addrs=self._ps_shard_addrs)
        # the freshness listener is engine-agnostic (a (time, clock)
        # callback) — carrying it over keeps the online deployment's
        # freshness chain intact across supervised restarts and
        # blue/green swaps without re-registration
        eng._reload_listener = self._reload_listener
        return eng

    @property
    def kv_pool_bytes(self) -> int:
        """On-device bytes of the target KV slot pool — the flat block
        arena for paged engines — (int8 codes + scales for
        ``kv_dtype="int8"`` pools, itemsize-true otherwise): the
        byte-accounting behind ``serving_quant_capacity_slots`` and
        ``serving_paged_capacity_slots``."""
        return _quant.kv_cache_bytes(self.caches)

    @property
    def kv_blocks_in_use(self) -> Optional[int]:
        """Paged engines: blocks currently HELD by live requests
        (privately-owned + trie-shared with ref > 0).  0 when idle —
        refcount-0 cached chains are reusable capacity, not leaks; the
        resilience matrix asserts this returns to 0 after every
        retirement path.  None for dense engines."""
        return None if self._pool is None else self._pool.in_use()

    def warmup(self) -> "ServingEngine":
        """Compile the engine's jitted programs before serving traffic: the
        decode step plus — in bucketed mode — EVERY bucket's batched
        prefill program and (when long prompts can chunk) the chunk-step
        programs.  A fresh engine otherwise pays each program's jit
        trace/compile inside the first real iteration that needs it —
        under an ``EngineSupervisor`` whose ``liveness_deadline`` is
        shorter than that compile, a cold engine is indistinguishable
        from a wedged one, so the supervisor warms every respawned clone
        before it goes live (cold jit must never read as a wedge under
        live traffic).  The prefill warmups target slot ``num_slots``, so
        every write drops on device — state is untouched.  Idempotent;
        fresh/idle engines only."""
        if self._active.any() or self._prefilling:
            raise RuntimeError("warmup() on an engine with active slots "
                               "would consume a real decode step")
        if self.prefill_mode == "eager":
            nxt, self.caches = self._step_fn(
                self.params, self.caches, jnp.asarray(self._cur_tok),
                jnp.asarray(self._positions), jnp.asarray(self._active),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._keys))
            jax.block_until_ready(nxt)
            # slot-write program: rewrite row 0 with a copy of itself (a
            # copy — the pool is donated, and XLA rejects donating a
            # buffer aliased by another argument; inactive slots hold junk
            # a prefill fully overwrites, so this is a no-op in the same
            # sense as the free-slot decode rows)
            row = tmap(lambda B: jnp.copy(B[0:1]), self.caches)
            self.caches = self._write_slot_fn(self.caches, row,
                                              jnp.int32(0))
            jax.block_until_ready(jax.tree_util.tree_leaves(self.caches)[0])
            return self
        # bucketed: one all-slots-inactive decode step (the speculative
        # round — draft steps + verify + back-fill — when a draft is
        # attached: a respawn under live traffic must pay zero jit on its
        # first real round)...
        if self.role == "prefill":
            # the token loop never runs on a prefill-role engine: skip
            # the decode-step warmup and warm the extraction gather
            # instead (all-null rows read the null block)
            rows = jnp.full((self._blocks_per_slot * self.block_size,),
                            self.kv_blocks * self.block_size, jnp.int32)
            jax.block_until_ready(jax.tree_util.tree_leaves(
                self._gather_fn(self.caches, rows))[0])
        elif self._draft_model is not None:
            (_, self.caches, self.d_caches, self._dev_tok,
             self._dev_pos) = self._spec_fn(
                self.params, self._draft_params, *self._state_args())
            jax.block_until_ready(self._dev_tok)
        else:
            out, self.caches, self._dev_pos = self._decode_fn(
                self.params, *self._state_args())
            self._dev_tok = out
            jax.block_until_ready(out)
        if self.role == "decode":
            # ingest program only: the bucket/chunk prefill programs are
            # never dispatched on a decode-role engine (admission is
            # submit_prefilled), so warming them would compile dead code.
            # Slot num_slots + mode="drop" installs nothing; the scatter
            # lands in the null block.
            n = self._blocks_per_slot * self.block_size
            rows = jnp.full((n,), self.kv_blocks * self.block_size,
                            jnp.int32)
            payload = [None if c is None else
                       {k: jnp.zeros((n,) + v.shape[1:], v.dtype)
                        for k, v in c.items()}
                       for c in self.caches]
            (self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
             self._dev_act, self._dev_temp, self._dev_topk,
             self._dev_topp, self._dev_keys) = self._ingest_fn(
                self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
                self._dev_act, self._dev_temp, self._dev_topk,
                self._dev_topp, self._dev_keys, rows, payload,
                jnp.int32(self.num_slots),
                jnp.full((self._t_tbl,), self.kv_blocks, jnp.int32),
                jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
                jnp.int32(0), jnp.float32(0.0),
                jnp.zeros((2,), jnp.uint32))
            jax.block_until_ready(jax.tree_util.tree_leaves(self.caches)[0])
            return self
        # ...every bucket's batched prefill program (all rows dropped;
        # quantized pools and draft-pool prefill compile here too — the
        # commit/quantize paths live inside these same programs; paged
        # warmups pass all-null block tables, so every cache write drops
        # into the null block)...
        nb = self.prefills_per_step
        drop = jnp.full((nb,), self.num_slots, jnp.int32)
        if self.paged:
            null_bt = jnp.full((nb, self._t_tbl), self.kv_blocks,
                               jnp.int32)
            null_dbt = (jnp.full((nb, self._d_tbl), self.kv_blocks,
                                 jnp.int32)
                        if self._draft_model is not None else None)
            # the copy-on-write block-copy program (null → null)
            if self._draft_model is None:
                self.caches = self._copy_fn(self.caches, self.kv_blocks,
                                            self.kv_blocks)
            else:
                self.caches, self.d_caches = self._copy_fn(
                    self.caches, self.d_caches, self.kv_blocks,
                    self.kv_blocks)
        for width in self._buckets:
            if self.paged:
                extra = [jnp.zeros((nb, width), jnp.int32),
                         jnp.zeros((nb,), jnp.int32),
                         jnp.ones((nb,), jnp.int32), drop, null_bt]
                if null_dbt is not None:
                    extra.append(null_dbt)
                self._apply_state(self._bucket_fn(width)(
                    *self._prog_args(), *extra,
                    jnp.zeros((nb,), jnp.float32),
                    jnp.zeros((nb,), jnp.int32),
                    jnp.zeros((nb,), jnp.float32),
                    jnp.zeros((nb, 2), jnp.uint32)))
            else:
                self._apply_state(self._bucket_fn(width)(
                    *self._prog_args(),
                    jnp.zeros((nb, width), jnp.int32),
                    jnp.ones((nb,), jnp.int32), drop,
                    jnp.zeros((nb,), jnp.float32),
                    jnp.zeros((nb,), jnp.int32),
                    jnp.zeros((nb,), jnp.float32),
                    jnp.zeros((nb, 2), jnp.uint32)))
        # ...and the chunk-step programs, when a prompt can be long enough
        # to take the chunked path at all
        if self.max_len > self.prefill_chunk:
            one = (jnp.zeros((1,), jnp.float32), jnp.zeros((1,), jnp.int32),
                   jnp.zeros((1,), jnp.float32),
                   jnp.zeros((1, 2), jnp.uint32))
            for width in sorted({self._chunk_width, *self._buckets}):
                toks = jnp.zeros((1, width), jnp.int32)
                if self.paged and not self.rolling:
                    off = jnp.zeros((1,), jnp.int32)
                    plen = jnp.ones((1,), jnp.int32)
                    bt1 = null_bt[:1]
                    if self._draft_model is not None:
                        self.caches, self.d_caches = self._stage_fn(width)(
                            self.params, self._draft_params, self.caches,
                            self.d_caches, toks, off, plen, bt1,
                            null_dbt[:1])
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), toks, self.num_slots,
                            off, plen, 0, bt1, null_dbt[:1], *one))
                    else:
                        self.caches = self._stage_fn(width)(
                            self.params, self.caches, toks, off, plen,
                            bt1)
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), toks, self.num_slots,
                            off, plen, 0, bt1, *one))
                    continue
                staging = init_cache(self.model, 1, self.max_len)
                if self._draft_model is not None:
                    d_staging = init_cache(self._draft_model, 1,
                                           self.max_len)
                    staging, d_staging = self._stage_fn(width)(
                        self.params, self._draft_params, staging,
                        d_staging, toks, 0)
                    if self.paged:  # rolling paged: block-table commit
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), staging, d_staging, toks,
                            self.num_slots, 0, 0, 1, null_bt[:1],
                            null_dbt[:1], *one))
                    else:
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), staging, d_staging, toks,
                            self.num_slots, 0, 0, 1, *one))
                else:
                    staging = self._stage_fn(width)(self.params, staging,
                                                    toks, 0)
                    if self.paged:
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), staging, toks,
                            self.num_slots, 0, 0, 1, null_bt[:1], *one))
                    else:
                        self._apply_state(self._final_fn(width)(
                            *self._prog_args(), staging, toks,
                            self.num_slots, 0, 0, 1, *one))
        # QoS engines also pre-pay the preemption swap programs: gather
        # (all-null rows read the null block) and ingest (slot num_slots
        # drops the install, the scatter lands in the null block) — a
        # first preemption under live overload must not stall the decode
        # loop a jit-compile long.
        with self._qlock:
            qos_on = bool(self._tenants)
        if self._can_preempt and qos_on:
            self._ensure_swap_fns()
            n = self._blocks_per_slot * self.block_size
            null_rows = jnp.full((n,), self.kv_blocks * self.block_size,
                                 jnp.int32)
            jax.block_until_ready(jax.tree_util.tree_leaves(
                self._swap_gather_fn(self.caches, null_rows, self._dev_tok,
                                     self._dev_pos, self._dev_keys,
                                     jnp.int32(0))[0])[0])
            payload = [None if c is None else
                       {k: jnp.zeros((n,) + v.shape[1:], v.dtype)
                        for k, v in c.items()}
                       for c in self.caches]
            (self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
             self._dev_act, self._dev_temp, self._dev_topk,
             self._dev_topp, self._dev_keys) = self._swap_ingest_fn(
                self.caches, self._dev_bt, self._dev_tok, self._dev_pos,
                self._dev_act, self._dev_temp, self._dev_topk,
                self._dev_topp, self._dev_keys, null_rows, payload,
                jnp.int32(self.num_slots),
                jnp.full((self._t_tbl,), self.kv_blocks, jnp.int32),
                jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
                jnp.int32(0), jnp.float32(0.0),
                jnp.zeros((2,), jnp.uint32))
        jax.block_until_ready(jax.tree_util.tree_leaves(self.caches)[0])
        return self

    def _loop(self) -> None:
        try:
            while self._running:
                if not self.step():
                    with self._qlock:
                        self._have_work.wait_for(
                            lambda: self._qdepth > 0 or bool(self._preempt_ids)
                            or not self._running,
                            timeout=0.05)
        except Exception as e:
            # a crashed decode loop fails loudly: every in-flight handle
            # gets a typed EngineDead instead of hanging its waiter
            logger.exception("serving engine decode loop crashed")
            self._declare_dead(e)

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------- hot reload (stretch)
    def attach_ps(self, host: str, port: int, every: int = 1,
                  retry_policy=None, shard_plan=None,
                  shard_addrs=None) -> None:
        """Hot weight reload: pull a fresh center from a live parameter
        server (the PS stack's ``'p'`` opcode — same wire the training
        workers speak) every ``every`` decode steps, so a training run and
        this engine share one deployment.  The pull happens BETWEEN decode
        steps — in-flight requests simply continue on the new weights (the
        KV cache keeps old-weight k/v until those positions roll out, the
        standard live-reload tradeoff).

        ``retry_policy`` (a ``resilience.RetryPolicy``) governs the
        RE-DIAL when the reload socket is down — a PS shard respawning on
        the same address (``ShardSupervisor``) comes back within a few
        tens of milliseconds, so a short bounded policy rides out the
        blip without abandoning the pull.  The default
        (:data:`DEFAULT_RELOAD_POLICY`) is deliberately tight: the pull
        runs on the decode thread between steps, so its worst case is a
        bounded serving stall, never an unbounded one.  A pull that fails
        past the policy counts ``stats["reload_failures"]`` and KEEPS the
        current weights — hot reload stays best-effort by design; the
        engine never dies on its PS.

        A SHARDED training PS (``ps_shards>1``) attaches by passing
        ``shard_plan`` + ``shard_addrs``: each pull gathers the center
        across every shard through a ``ps_sharding.ShardedPSClient``
        (scatter/gather over the same 'p' wire), so the engine never
        hot-reloads one shard's torn slice.  The gathered view is
        epoch-wave consistent — per-shard slices are each snapshotted
        under their own apply lock, the same consistency the sharded
        checkpoint path provides — and a pull that loses ANY shard past
        the policy keeps the current weights wholesale (all-or-nothing,
        never a partial swap).  ``(host, port)`` must be shard 0's
        address (the canonical deployment handle)."""
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if (shard_plan is None) != (shard_addrs is None):
            raise ValueError(
                "shard_plan and shard_addrs come as a pair — both for a "
                "sharded PS attachment, neither for a single server")
        if shard_addrs is not None and len(shard_addrs) < 2:
            # the N=1 plan is the identity partition: the plain single-
            # socket pull already returns the full center
            shard_plan = shard_addrs = None
        self._ps_addr = (host, int(port))
        self._reload_policy = retry_policy
        self._reload_every = int(every)
        self._ps_shard_plan = shard_plan
        self._ps_shard_addrs = (None if shard_addrs is None else
                                [(str(h), int(p)) for h, p in shard_addrs])

    def _pull_sharded(self) -> Dict[str, Any]:
        """One gathered pull over every shard (sharded attach_ps) —
        returns the same ``{"weights", "clock"}`` shape the single-socket
        'p' reply carries, with the clock summed over shards (each shard
        counts its own applies; the sum is the total-updates center
        generation, monotone across shard respawns by the client's
        per-shard monotonic clock view)."""
        if self._reload_client is None:
            from .ps_sharding import ShardedPSClient
            policy = (self._reload_policy if self._reload_policy
                      is not None else DEFAULT_RELOAD_POLICY)
            client = ShardedPSClient(self._ps_shard_plan,
                                     self._ps_shard_addrs,
                                     recovery=True, policy=policy)
            client.connect(policy=policy)
            self._reload_client = client
        weights = self._reload_client.pull()
        return {"weights": weights,
                "clock": sum(self._reload_client._clocks)}

    def _pull_weights(self) -> None:
        try:
            if self._ps_shard_addrs is not None:
                msg = self._pull_sharded()
            else:
                if self._reload_sock is None:
                    from . import resilience
                    policy = (self._reload_policy if self._reload_policy
                              is not None else DEFAULT_RELOAD_POLICY)
                    self._reload_sock = resilience.dial(*self._ps_addr,
                                                        policy=policy)
                networking.send_opcode(self._reload_sock, b"p")
                msg = networking.recv_data(self._reload_sock,
                                           pool=self._reload_pool)
            if self.quantize is not None:
                # re-quantize the pulled center through the SAME path the
                # constructor used — never swap raw fp32 weights into a
                # quantized engine (the f32 skeleton maps the flat wire
                # list back onto the pre-quant pytree first)
                fresh = self.model.set_weights(self._fp_skel,
                                               msg["weights"])
                self.params = _quantize_weights(fresh, self.quantize)
            else:
                self.params = self.model.set_weights(self.params,
                                                     msg["weights"])
            if self.prefill_mode == "bucketed":
                # keep the weights device-resident: the decode loop's
                # zero-upload contract must survive a reload
                self.params = jax.device_put(self.params)
            self.stats["weight_reloads"] += 1
            self.stats["reloads"] += 1
            clock = msg.get("clock") if isinstance(msg, dict) else None
            if clock is not None:
                self.stats["center_generation"] = int(clock)
            listener = self._reload_listener
            if listener is not None:
                try:
                    listener(time.monotonic(),
                             self.stats["center_generation"])
                except Exception:   # freshness is observability, not
                    logger.exception(  # control flow — never kill decode
                        "hot-reload listener raised")
        except (ConnectionError, OSError, ValueError) as e:
            self.stats["reload_failures"] += 1
            logger.warning("serving hot-reload pull failed (%s); keeping "
                           "current weights", e)
            if self._reload_sock is not None:
                try:
                    self._reload_sock.close()
                except OSError:
                    pass
                self._reload_sock = None
            if self._reload_client is not None:
                try:
                    self._reload_client.disconnect()
                except (OSError, ConnectionError):
                    pass
                self._reload_client = None


# ---------------------------------------------------------------------------
# wire layer: the serving protocol over the shared frame codec
# ---------------------------------------------------------------------------

#: serving-protocol opcodes (this protocol's own namespace — a serving
#: server port never speaks the PS protocol): 'q' enqueue request (frame:
#: prompt + sampling params → ack/backpressure reply), 'r' stream reply
#: (frame: {"id"} → chunk frames until {"done": True}), 'x' cancel (frame:
#: {"id"} → ack; mid-stream it is unacked — the stream's final frame
#: carries finish="cancel").
OP_ENQUEUE = networking.SERVING_OP_ENQUEUE
OP_STREAM = networking.SERVING_OP_STREAM
OP_CANCEL = networking.SERVING_OP_CANCEL
OP_KVBLOCKS = networking.SERVING_OP_KVBLOCKS
OP_STATS = networking.SERVING_OP_STATS

#: the selectable serving transport cores (``server_core=`` on
#: :class:`ServingServer`): ``"threaded"`` is the seed's
#: thread-per-connection handler, ``"event"`` the one-selector I/O loop
#: (the ``parameter_servers.PS_CORES`` twin — same knob idiom)
SERVING_CORES = ("threaded", "event")

#: event-core receive chunk: big enough that a steady-state request frame
#: lands complete in ONE recv (the parser's zero-copy fast path); larger
#: frames reassemble through the parser accumulator
_EV_RECV_CHUNK = 1 << 20

#: frames coalesced per ``sendmsg`` — comfortably under IOV_MAX, and one
#: loop wake rarely owes a connection more than a few token chunks
_EV_SENDMSG_BATCH = 64


class _EvPoisoned:
    """A deferred KV-block payload that failed its transport-boundary
    ``validate()`` while being deep-copied out of the receive scratch —
    the rejection is replayed when the deferred op is dispatched, so a
    hostile pipelined ``'k'`` sheds the connection through the same
    ``ProtocolError`` path the threaded core uses."""

    __slots__ = ("error",)

    def __init__(self, error: str):
        self.error = str(error)


def _deepcopy_wire_msg(msg: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-copy a parsed wire message whose ndarray leaves are zero-copy
    views into the connection's receive scratch.  Deferred (pipelined)
    ops outlive that scratch — the next ``recv_into`` overwrites it — so
    views must be promoted to owned memory at deferral time."""
    out: Dict[str, Any] = {}
    for k, v in msg.items():
        if isinstance(v, np.ndarray):
            out[k] = np.array(v, copy=True)
        elif isinstance(v, networking.KVBlocks):
            try:
                out[k] = v.validate().decoded()
            except ValueError as e:  # replayed at dispatch (see above)
                out[k] = _EvPoisoned(str(e))
        else:
            out[k] = v
    return out


class _ServingConn:
    """Per-connection state on the serving event loop: the incremental
    frame parser over a pooled receive scratch, the pending-write queue
    with its encode pool, and the streaming-relay state (the handle being
    pumped, ops the client pipelined past it, backpressure flags).

    Touched ONLY on the loop thread — no lock.  The decoded-view lifetime
    contract matches the PS event core: every parsed op is consumed (or
    deep-copied into ``deferred``) before this connection's next
    ``recv_into`` can overwrite the scratch."""

    __slots__ = ("sock", "parser", "out", "out_bytes", "recv_pool",
                 "send_pool", "want_write", "paused", "stream", "deferred",
                 "last_progress", "closed")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.parser = networking.FrameParser(
            frame_ops=OP_ENQUEUE + OP_STREAM + OP_CANCEL + OP_KVBLOCKS)
        self.out: List[memoryview] = []
        self.out_bytes = 0
        self.recv_pool = networking.BufferPool()
        self.send_pool = networking.BufferPool()
        self.want_write = False
        self.paused = False   # backpressure: reads masked off, pump held
        self.stream: Optional[RequestHandle] = None  # handle mid-relay
        self.deferred: List[Tuple[bytes, Dict[str, Any]]] = []
        self.last_progress = 0.0  # perf_counter of the last stream chunk
        self.closed = False


class ServingServer:
    """TCP front-end for a :class:`ServingEngine` — same accept-loop /
    frame-codec / BufferPool idiom as ``SocketParameterServer``, so serving
    clients speak the exact wire the PS stack already speaks.

    Two transport cores behind one constructor knob (``server_core``, the
    ``parameter_servers.PS_CORES`` idiom): ``"threaded"`` (default) keeps
    the seed's thread-per-connection handler bit-identical; ``"event"``
    multiplexes every connection on ONE selector I/O thread
    (``dkt-serving-io``) — per-connection read/write buffers over the
    incremental ``networking.FrameParser``, token frames flushed through
    a socketpair waker when the engine thread pushes (no per-connection
    thread), non-blocking coalesced writes so a slow client never pins
    the relay, and a per-connection outbound cap (``max_conn_buffer``)
    that stops reading from — and pumping to — a client that stops
    reading us.  Protocol, typed errors, counters, and the failure matrix
    below are identical on both cores (docs/serving.md "Event
    transport").

    Per connection: ``'q'`` + request frame → ack ``{"ok": True, "id": n}``
    or a typed rejection (``kind`` ``"backpressure"`` / ``"draining"`` /
    ``"engine_dead"`` / ``"bad_request"``); ``'r'`` + ``{"id": n}`` → a
    stream of ``{"id", "tokens", "done"}`` chunk frames, the last one
    carrying ``done=True`` + ``finish`` (eos/length/deadline/cancel/…) +
    the final padded ``row`` (or a typed error instead of a row when the
    engine died); ``'x'`` + ``{"id": n}`` → cancel ack.  EOF closes the
    connection; the engine keeps running.

    Failure semantics (this is the client-disconnect reclamation layer):

     - every empty stream-poll slice (``poll_s``) checks the client socket
       — EOF/RST cancels the streamed request, so an abandoned connection
       reclaims its KV slot within one scheduler iteration of detection
       instead of decoding to completion;
     - a request is *owned* by the connection that submitted it (ownership
       transfers to whichever connection streams it); when a connection
       dies, its unfinished owned requests are cancelled
       (``cancel_on_disconnect``, default True) and their handles
       released — a dead client leaks neither slots nor handle entries;
     - a stream that makes no progress is bounded by the request deadline
       (plus a grace period) or, for deadline-less requests, by
       ``stream_timeout_s`` — a stalled engine gets a typed ``"stall"``
       error frame instead of pinning the handler thread for a fixed
       minute;
     - a torn/corrupt frame (``protocol_errors``) or transport fault
       (``disconnects``) sheds the connection silently; its pooled
       buffers are per-handler locals so they are released with it, and
       ``live_connections`` decrements (asserted in
       tests/test_serving_resilience.py).
    """

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, stream_timeout_s: float = 60.0,
                 poll_s: float = 0.02, cancel_on_disconnect: bool = True,
                 server_core: str = "threaded",
                 max_conn_buffer: int = 1 << 20):
        if server_core not in SERVING_CORES:
            raise ValueError(f"server_core must be one of "
                             f"{sorted(SERVING_CORES)}, got {server_core!r}")
        self.engine = engine
        self.host = host
        self.port = int(port)
        self.stream_timeout_s = float(stream_timeout_s)
        self.poll_s = float(poll_s)
        self.cancel_on_disconnect = bool(cancel_on_disconnect)
        self.server_core = server_core
        #: event core only: per-connection outbound-buffer cap in bytes.
        #: A client that stops reading its token stream grows the pending
        #: write queue; past this cap the loop stops reading from AND
        #: pumping to that connection until the flush drains below half
        #: the cap (the PS core's oversize-guard idiom, per connection).
        self.max_conn_buffer = int(max_conn_buffer)
        self._handles: Dict[int, RequestHandle] = {}
        #: request id → owning connection (submitting conn, re-claimed by
        #: the streaming conn) — the disconnect-reclamation bookkeeping
        self._owner: Dict[int, socket.socket] = {}
        self._hlock = threading.Lock()
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()  # guards: _conns
        #: lock-free stop flag: written once by start()/stop(), polled by
        #: the accept path on either core — monotonic, so races are benign
        self._running = False
        #: event core: the shared I/O loop and its per-socket conn state
        #: (the latter touched ONLY on the loop thread — no lock)
        self._loop: Optional[networking.EventLoop] = None
        self._econns: Dict[socket.socket, _ServingConn] = {}
        self.disconnects = 0       # transport faults / EOF mid-frame
        self.protocol_errors = 0   # corrupt frames (bad magic, length lies)
        self.disconnect_cancels = 0  # requests reclaimed from dead clients

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def live_connections(self) -> int:
        """Open client connections with a live handler (the serving twin of
        ``SocketParameterServer.live_connections`` — shed connections must
        decrement this, pooled buffers and all)."""
        with self._lock:
            return len(self._conns)

    def start(self) -> "ServingServer":
        self.engine.start()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(128)
        self._running = True
        if self.server_core == "event":
            self._server.setblocking(False)
            self._loop = networking.EventLoop(name="dkt-serving-io")
            self._loop.stop_hooks.append(self._ev_shutdown)
            self._loop.start()
            self._loop.call_soon(
                lambda: self._loop.add(self._server, self._ev_accept))
            # the name is load-bearing: supervisors probe server liveness
            # through ``_accept_thread.is_alive()`` on either core
            self._accept_thread = self._loop.thread
        else:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, daemon=True,
                name="dkt-serving-accept")
            self._accept_thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._running = False
        if self.server_core == "event":
            loop = self._loop
            if loop is not None and not loop.stop(join_timeout=join_timeout):
                # wedged inside a callback (the loop itself never blocks
                # on a socket): force-close everything from here so the
                # wedged thread fails fast on its next socket op and a
                # same-address respawn can bind
                logger.warning(
                    "serving I/O loop still alive after stop(join_timeout="
                    "%.1fs); force-closing its connections and listener",
                    join_timeout)
                with self._lock:
                    conns = list(self._conns)
                    self._conns.clear()
                for c in conns:
                    networking._hard_close(c)
                if self._server is not None:
                    try:
                        self._server.close()
                    except OSError:
                        pass
            self.engine.stop()
            return
        if self._server is not None:
            try:  # wake the blocked accept()
                socket.create_connection((self.host, self.port),
                                         timeout=1.0).close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=join_timeout)
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self.engine.stop()

    def respawn_clone(self, engine: Optional[ServingEngine] = None
                      ) -> "ServingServer":
        """A same-core replacement server on this address with every
        transport knob carried over — ``server_core`` included, so a
        supervisor restart never silently changes the I/O architecture.
        ``engine`` defaults to this server's (the ``EngineSupervisor``
        already re-points ``.engine`` in place; this seam is for the
        whole-server restart path, mirroring
        ``SocketParameterServer.respawn_clone``)."""
        return ServingServer(
            engine if engine is not None else self.engine,
            host=self.host, port=self.port,
            stream_timeout_s=self.stream_timeout_s, poll_s=self.poll_s,
            cancel_on_disconnect=self.cancel_on_disconnect,
            server_core=self.server_core,
            max_conn_buffer=self.max_conn_buffer)

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            if not self._running:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True, name="dkt-serving-conn").start()

    def _handle(self, conn: socket.socket) -> None:
        # per-connection pools: requests land in a reusable receive buffer,
        # replies re-serialize into a reusable send buffer.  The send pool
        # is per-connection (BufferPool is lock-protected, but a shared
        # pool would still let another connection's encode overwrite a
        # frame between encode and sendall).  Both are handler locals, so
        # every exit path — clean EOF, torn frame, transport fault —
        # releases them with the handler.
        recv_pool = networking.BufferPool()
        send_pool = networking.BufferPool()
        pending_op = b""  # opcode the client pipelined during a stream
        try:
            while True:
                if pending_op:
                    op, pending_op = pending_op, b""
                else:
                    op = networking.recv_opcode(conn)
                if op == b"":
                    return
                if op == OP_ENQUEUE:
                    msg = networking.recv_data(conn, pool=recv_pool)
                    try:
                        h = self.engine.submit(
                            np.array(msg["prompt"], np.int32, copy=True),
                            int(msg["num_steps"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            top_k=msg.get("top_k"),
                            top_p=msg.get("top_p"),
                            eos_id=msg.get("eos_id"),
                            pad_id=msg.get("pad_id"),
                            seed=int(msg.get("seed", 0)),
                            deadline_s=msg.get("deadline_s"),
                            tenant=msg.get("tenant"),
                            priority=int(msg.get("priority", 0)),
                            block=False)
                    except QuotaExceeded as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "quota"}, pool=send_pool)
                        continue
                    except QueueFull:
                        networking.send_data(
                            conn, {"ok": False, "error": "queue full",
                                   "kind": "backpressure"},
                            pool=send_pool)
                        continue
                    except Draining as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "draining"}, pool=send_pool)
                        continue
                    except EngineDead as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "engine_dead"}, pool=send_pool)
                        continue
                    except ValueError as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "bad_request"}, pool=send_pool)
                        continue
                    with self._hlock:
                        self._handles[h.id] = h
                        self._owner[h.id] = conn
                    networking.send_data(conn, {"ok": True, "id": h.id},
                                         pool=send_pool)
                elif op == OP_KVBLOCKS:
                    # disaggregated hand-off: a prefill engine (via
                    # DisaggPair) ships a request's filled KV blocks.
                    # validate() runs BEFORE any engine call — a
                    # hostile/torn payload raises ProtocolError (a
                    # ValueError) out to the shed path below with the
                    # receiving pool untouched; decoded() copies the
                    # pooled recv views before they die on the next recv.
                    msg = networking.recv_data(conn, pool=recv_pool)
                    kvb = msg.get("blocks")
                    if not isinstance(kvb, networking.KVBlocks):
                        raise networking.ProtocolError(
                            "kv-block frame carries no KVBlocks payload")
                    kvb = kvb.validate().decoded()
                    try:
                        h = self.engine.submit_prefilled(
                            kvb,
                            np.array(msg["prompt"], np.int32, copy=True),
                            int(msg["first_token"]),
                            int(msg["num_steps"]),
                            temperature=float(msg.get("temperature", 0.0)),
                            top_k=msg.get("top_k"),
                            top_p=msg.get("top_p"),
                            eos_id=msg.get("eos_id"),
                            pad_id=msg.get("pad_id"),
                            deadline_s=msg.get("deadline_s"),
                            tenant=msg.get("tenant"),
                            priority=int(msg.get("priority", 0)),
                            block=False)
                    except QuotaExceeded as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "quota"}, pool=send_pool)
                        continue
                    except QueueFull:
                        networking.send_data(
                            conn, {"ok": False, "error": "queue full",
                                   "kind": "backpressure"},
                            pool=send_pool)
                        continue
                    except Draining as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "draining"}, pool=send_pool)
                        continue
                    except EngineDead as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "engine_dead"}, pool=send_pool)
                        continue
                    except ValueError as e:
                        networking.send_data(
                            conn, {"ok": False, "error": str(e),
                                   "kind": "bad_request"}, pool=send_pool)
                        continue
                    with self._hlock:
                        self._handles[h.id] = h
                        self._owner[h.id] = conn
                    networking.send_data(conn, {"ok": True, "id": h.id},
                                         pool=send_pool)
                elif op == OP_STREAM:
                    msg = networking.recv_data(conn, pool=recv_pool)
                    rid = int(msg["id"])
                    with self._hlock:
                        h = self._handles.get(rid)
                        if h is not None:
                            self._owner[rid] = conn  # stream claims it
                    if h is None:
                        networking.send_data(
                            conn, {"ok": False, "done": True,
                                   "kind": "unknown_id",
                                   "error": f"unknown id {rid}"},
                            pool=send_pool)
                        continue
                    alive, pending_op = self._stream(conn, h, recv_pool,
                                                     send_pool)
                    if not alive:
                        return  # client gone mid-stream (finally reclaims)
                elif op == OP_CANCEL:
                    msg = networking.recv_data(conn, pool=recv_pool)
                    with self._hlock:
                        h = self._handles.get(int(msg["id"]))
                    ok = h is not None and self.engine.cancel(h)
                    networking.send_data(
                        conn, {"ok": True, "cancelled": bool(ok)},
                        pool=send_pool)
                elif op == OP_STATS:
                    # load probe (no request body): the engine's lock-free
                    # snapshot, the signal a ServingRouter dispatches on
                    networking.send_data(
                        conn, {"ok": True, "load": self.engine.load()},
                        pool=send_pool)
                else:
                    return  # protocol violation: drop the connection
        except ValueError:
            self.protocol_errors += 1  # corrupt frame: shed silently
            return
        except (ConnectionError, OSError):
            self.disconnects += 1  # incl. a half-frame EOF/RST mid-recv
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            self._release_owned(conn)

    def _release_owned(self, conn: socket.socket) -> None:
        """Disconnect reclamation: cancel this connection's unfinished
        requests and drop their handle entries — a dead client's KV slot is
        back in the pool within one scheduler iteration, and the handle
        table does not grow with abandoned ids."""
        with self._hlock:
            owned = [rid for rid, c in self._owner.items() if c is conn]
            handles = [self._handles.pop(rid, None) for rid in owned]
            for rid in owned:
                self._owner.pop(rid, None)
        if not self.cancel_on_disconnect:
            return
        for h in handles:
            if h is not None and self.engine.cancel(h):
                self.disconnect_cancels += 1

    def _stream(self, conn: socket.socket, h: RequestHandle,
                recv_pool: "networking.BufferPool",
                send_pool: "networking.BufferPool"
                ) -> Tuple[bool, bytes]:
        """Relay ``h``'s token chunks until its final frame.  Bounded
        waits: each empty ``poll_s`` slice checks the client socket for
        EOF/RST (→ cancel + reclaim) or a mid-stream ``'x'`` cancel
        opcode; a stream with no progress past the request deadline (+
        grace) or ``stream_timeout_s`` sends a typed ``"stall"`` error
        frame.  Returns ``(alive, pending_op)``: ``alive`` is False when
        the connection is gone; ``pending_op`` is an opcode the client
        pipelined while the stream was relaying, for ``_handle`` to
        process after the final frame."""
        grace = max(1.0, 4 * self.poll_s)
        waited = 0.0
        pending = b""
        while True:
            # check the client side EVERY iteration (not just idle slices):
            # a mid-stream cancel or disconnect must land even while chunks
            # are flowing back-to-back.  Once the client pipelines its next
            # opcode ('q'/'r'), STOP reading — the following bytes are that
            # request's frame, owned by _handle after this stream's final
            # frame (a disconnect is still caught by the send path below).
            if not pending:
                status = self._poll_client(conn, recv_pool)
                if status == "dead":
                    if self.cancel_on_disconnect:
                        self.engine.cancel(h)
                    return False, b""
                if isinstance(status, bytes):
                    pending = status
            chunk, done = h.next_chunk(timeout=self.poll_s)
            if not done and not len(chunk):
                waited += self.poll_s
                now = time.perf_counter()
                stalled = (now > h.deadline + grace
                           if h.deadline is not None
                           else waited >= self.stream_timeout_s)
                if stalled:
                    # the engine should have retired this request by now —
                    # it is wedged or dead; unblock the client with a typed
                    # error frame instead of holding the handler thread
                    with self._hlock:
                        self._handles.pop(h.id, None)
                        self._owner.pop(h.id, None)
                    try:
                        networking.send_data(
                            conn, {"id": h.id, "ok": False, "done": True,
                                   "tokens": np.zeros(0, np.int32),
                                   "finish": "error", "kind": "stall",
                                   "error": f"no progress on request "
                                            f"{h.id} (engine stalled)"},
                            pool=send_pool)
                    except (ConnectionError, OSError):
                        return False, b""
                    return True, pending
                continue
            waited = 0.0
            reply: Dict[str, Any] = {"id": h.id, "tokens": chunk,
                                     "done": done}
            if done:
                reply["finish"] = h.finish
                if h.error is not None:
                    reply["ok"] = False
                    reply["kind"] = "engine_dead"
                    reply["error"] = str(h.error)
                else:
                    reply["row"] = h.result()
            try:
                networking.send_data(conn, reply, pool=send_pool)
            except (ConnectionError, OSError):
                if self.cancel_on_disconnect:
                    self.engine.cancel(h)
                return False, b""
            if done:
                with self._hlock:
                    self._handles.pop(h.id, None)
                    self._owner.pop(h.id, None)
                return True, pending

    def _poll_client(self, conn: socket.socket,
                     recv_pool: "networking.BufferPool"
                     ) -> Union[str, bytes]:
        """Non-blocking client-socket check between stream chunks:
        ``"idle"`` (nothing to read — the normal case), ``"dead"``
        (EOF/RST/garbage — the disconnect-reclamation trigger), ``"ok"``
        after consuming a mid-stream ``'x'`` cancel (any id; unacked —
        the stream's final frame is the acknowledgement), or the opcode
        byte itself when the client pipelined its next ``'q'``/``'r'``
        request while this stream is still relaying (stashed by
        ``_stream``, processed after the final frame — pipelining is not
        a protocol violation)."""
        try:
            readable, _, _ = select.select([conn], [], [], 0)
            if not readable:
                return "idle"
            op = conn.recv(1)
            if op == OP_CANCEL:
                # the cancel payload may trail the opcode across packets:
                # bound the recv so a torn/stalled cancel frame cannot pin
                # the stream relay (timeout → OSError → "dead")
                conn.settimeout(1.0)
                try:
                    msg = networking.recv_data(conn, pool=recv_pool)
                finally:
                    conn.settimeout(None)
                with self._hlock:
                    target = self._handles.get(int(msg["id"]))
                if target is not None:
                    self.engine.cancel(target)
                return "ok"
            if op in (OP_ENQUEUE, OP_STREAM, OP_KVBLOCKS):
                return op  # pipelined next request, not a dead client
        except (ConnectionError, OSError, ValueError):
            return "dead"
        # EOF (b"") or mid-stream protocol violation: the client is gone
        return "dead"

    # -- the event core ------------------------------------------------------
    # One selector I/O thread ("dkt-serving-io") multiplexes every client
    # connection: accept, parse, dispatch, stream-relay, and flush all run
    # as EventLoop callbacks, so 64 concurrent wire streams cost 64
    # registered fds instead of 64 handler threads.  Token frames reach
    # the loop through RequestHandle.set_listener → call_soon (the
    # socketpair waker), and every method below runs ON the loop thread —
    # _econns and _ServingConn state need no lock.  Semantics (typed
    # rejections, mid-stream 'x', pipelining, stall bounds, disconnect
    # reclamation, counters) mirror the threaded handler above, clause
    # for clause.

    def _ev_accept(self, mask: int) -> None:
        while True:
            try:
                sock, _ = self._server.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if not self._running:
                try:
                    sock.close()
                except OSError:
                    pass
                return
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = _ServingConn(sock)
            self._econns[sock] = conn
            with self._lock:
                self._conns.append(sock)
            self._loop.add(sock, lambda m, c=conn: self._ev_io(c, m))

    def _ev_io(self, conn: _ServingConn, mask: int) -> None:
        if conn.closed:
            return
        if mask & selectors.EVENT_WRITE:
            self._ev_flush(conn)
        if conn.closed or conn.paused:
            return
        if mask & selectors.EVENT_READ:
            self._ev_read(conn)

    def _ev_read(self, conn: _ServingConn) -> None:
        # drain ops already parsed first (a mid-batch backpressure pause
        # abandons the messages() walk; the resume path re-enters here
        # with no new bytes owed by the socket)
        if self._ev_drain_parsed(conn):
            return
        while not conn.closed and not conn.paused:
            # direct-fill continuation for a frame torn across recvs,
            # else land the bytes in the pooled scratch and decode
            # zero-copy views over it (the PS event core's read path)
            target = conn.parser.writable()
            fed_scratch = target is None
            if fed_scratch:
                target = memoryview(conn.recv_pool.get(_EV_RECV_CHUNK))
            try:
                n = conn.sock.recv_into(target)
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionError, OSError):
                self._ev_conn_lost(conn, fault=conn.parser.midframe)
                return
            if not n:
                # EOF: clean at a frame boundary (no counter — the
                # threaded recv_opcode contract), a torn frame otherwise
                self._ev_conn_lost(conn, fault=conn.parser.midframe)
                return
            if fed_scratch:
                conn.parser.feed(target[:n])
            else:
                conn.parser.advance(n)
            if self._ev_drain_parsed(conn):
                return  # dispatched >= 1 op: yield the loop (fairness);
                # the level-triggered selector re-arms for the rest

    def _ev_drain_parsed(self, conn: _ServingConn) -> bool:
        """Dispatch every op the parser has buffered.  Returns True when
        at least one op was dispatched or the connection died (the read
        loop yields), False when more bytes are needed."""
        got = False
        try:
            for op, msg in conn.parser.messages():
                got = True
                self._ev_dispatch(conn, op, msg)
                if conn.closed or conn.paused:
                    return True
        except ValueError:
            if conn.stream is not None:
                # mid-stream garbage/torn frame: the threaded core's
                # _poll_client "dead" verdict — cancel + shed, no counter
                self._ev_conn_lost(conn, fault=False)
            else:
                self.protocol_errors += 1  # corrupt frame: shed silently
                self._ev_close(conn)
            return True
        except Exception:
            logger.exception(
                "serving event dispatch failed; shedding the connection "
                "(threaded-core parity: its handler thread died with it)")
            self._ev_close(conn)
            return True
        return got

    def _ev_dispatch(self, conn: _ServingConn, op: Optional[bytes],
                     msg) -> None:
        if conn.stream is not None:
            # mid-stream: the threaded core's _poll_client contract
            if op == OP_CANCEL:
                with self._hlock:
                    target = self._handles.get(int(msg["id"]))
                if target is not None:
                    self.engine.cancel(target)
                return  # unacked: the stream's final frame acknowledges
            if op in (OP_ENQUEUE, OP_STREAM, OP_KVBLOCKS):
                # pipelined next request: deferred past the final frame,
                # deep-copied out of the recv scratch its views die with
                conn.deferred.append((op, _deepcopy_wire_msg(msg)))
                return
            self._ev_conn_lost(conn, fault=False)  # protocol violation
            return
        if msg is None:
            if op == OP_STATS:
                # load probe, answered inline on the loop (no request
                # body): the engine's lock-free snapshot, piggybacked on
                # whatever flush this wake already owes the connection
                self._ev_queue(conn, {"ok": True,
                                      "load": self.engine.load()})
            else:
                self._ev_close(conn)  # protocol violation: drop silently
            return
        if op in (OP_ENQUEUE, OP_KVBLOCKS):
            self._ev_submit(conn, op, msg)
        elif op == OP_STREAM:
            rid = int(msg["id"])
            with self._hlock:
                h = self._handles.get(rid)
                if h is not None:
                    self._owner[rid] = conn.sock  # stream claims it
            if h is None:
                self._ev_queue(conn, {"ok": False, "done": True,
                                      "kind": "unknown_id",
                                      "error": f"unknown id {rid}"})
                return
            self._ev_start_stream(conn, h)
        elif op == OP_CANCEL:
            with self._hlock:
                h = self._handles.get(int(msg["id"]))
            ok = h is not None and self.engine.cancel(h)
            self._ev_queue(conn, {"ok": True, "cancelled": bool(ok)})

    def _ev_submit(self, conn: _ServingConn, op: bytes, msg) -> None:
        """``'q'``/``'k'`` admission with the threaded core's exact typed
        rejection chain.  A ``ProtocolError`` (hostile KV payload)
        re-raises past the bad_request catch so the connection is shed
        and counted as a protocol error, with no engine call made."""
        try:
            if op == OP_KVBLOCKS:
                kvb = msg.get("blocks")
                if isinstance(kvb, _EvPoisoned):
                    raise networking.ProtocolError(kvb.error)
                if not isinstance(kvb, networking.KVBlocks):
                    raise networking.ProtocolError(
                        "kv-block frame carries no KVBlocks payload")
                kvb = kvb.validate().decoded()
                h = self.engine.submit_prefilled(
                    kvb, np.array(msg["prompt"], np.int32, copy=True),
                    int(msg["first_token"]), int(msg["num_steps"]),
                    temperature=float(msg.get("temperature", 0.0)),
                    top_k=msg.get("top_k"), top_p=msg.get("top_p"),
                    eos_id=msg.get("eos_id"), pad_id=msg.get("pad_id"),
                    deadline_s=msg.get("deadline_s"),
                    tenant=msg.get("tenant"),
                    priority=int(msg.get("priority", 0)), block=False)
            else:
                h = self.engine.submit(
                    np.array(msg["prompt"], np.int32, copy=True),
                    int(msg["num_steps"]),
                    temperature=float(msg.get("temperature", 0.0)),
                    top_k=msg.get("top_k"), top_p=msg.get("top_p"),
                    eos_id=msg.get("eos_id"), pad_id=msg.get("pad_id"),
                    seed=int(msg.get("seed", 0)),
                    deadline_s=msg.get("deadline_s"),
                    tenant=msg.get("tenant"),
                    priority=int(msg.get("priority", 0)), block=False)
        except QuotaExceeded as e:
            self._ev_queue(conn, {"ok": False, "error": str(e),
                                  "kind": "quota"})
            return
        except QueueFull:
            self._ev_queue(conn, {"ok": False, "error": "queue full",
                                  "kind": "backpressure"})
            return
        except Draining as e:
            self._ev_queue(conn, {"ok": False, "error": str(e),
                                  "kind": "draining"})
            return
        except EngineDead as e:
            self._ev_queue(conn, {"ok": False, "error": str(e),
                                  "kind": "engine_dead"})
            return
        except networking.ProtocolError:
            raise  # transport-boundary rejection: shed, don't reply
        except ValueError as e:
            self._ev_queue(conn, {"ok": False, "error": str(e),
                                  "kind": "bad_request"})
            return
        with self._hlock:
            self._handles[h.id] = h
            self._owner[h.id] = conn.sock
        self._ev_queue(conn, {"ok": True, "id": h.id})

    # -- event-core stream relay --------------------------------------------
    def _ev_start_stream(self, conn: _ServingConn,
                         h: RequestHandle) -> None:
        conn.stream = h
        conn.last_progress = time.perf_counter()
        loop = self._loop

        def poke(c=conn, hh=h):
            loop.call_soon(lambda: self._ev_pump(c, hh))

        h.set_listener(poke)  # fires once now if progress predates it
        self._ev_schedule_stall(conn, h)
        self._ev_pump(conn, h)

    def _ev_pump(self, conn: _ServingConn, h: RequestHandle) -> None:
        """Relay every token chunk ``h`` has ready onto ``conn``'s write
        queue — the event twin of ``_stream``'s relay body.  Invoked via
        the handle's listener on every engine push (duplicate wakes are
        cheap no-ops) and from the backpressure resume path."""
        if conn.closed or conn.stream is not h or conn.paused:
            return
        while True:
            chunk, done = h.next_chunk(timeout=0)
            if not done and not len(chunk):
                return
            conn.last_progress = time.perf_counter()
            reply: Dict[str, Any] = {"id": h.id, "tokens": chunk,
                                     "done": done}
            if done:
                reply["finish"] = h.finish
                if h.error is not None:
                    reply["ok"] = False
                    reply["kind"] = "engine_dead"
                    reply["error"] = str(h.error)
                else:
                    reply["row"] = h.result()
            self._ev_queue(conn, reply)
            if conn.closed:
                return  # the flush tore the connection down mid-relay
            if done:
                self._ev_end_stream(conn, h)
                return
            if conn.paused:
                return  # backpressure: the flush path resumes the pump

    def _ev_end_stream(self, conn: _ServingConn,
                       h: RequestHandle) -> None:
        with self._hlock:
            self._handles.pop(h.id, None)
            self._owner.pop(h.id, None)
        h.set_listener(None)
        conn.stream = None
        self._ev_drain_deferred(conn)

    def _ev_drain_deferred(self, conn: _ServingConn) -> None:
        """Dispatch ops the client pipelined during a stream (the
        threaded core's ``pending_op``, processed after the final
        frame).  A deferred ``'r'`` re-enters streaming; anything still
        queued behind it stays deferred, in order, for that stream's
        end."""
        while (conn.deferred and not conn.closed and not conn.paused
                and conn.stream is None):
            op, msg = conn.deferred.pop(0)
            try:
                self._ev_dispatch(conn, op, msg)
            except ValueError:
                if conn.stream is not None:
                    self._ev_conn_lost(conn, fault=False)
                else:
                    self.protocol_errors += 1
                    self._ev_close(conn)
                return
            except Exception:
                logger.exception("serving event dispatch failed; "
                                 "shedding the connection")
                self._ev_close(conn)
                return

    def _ev_schedule_stall(self, conn: _ServingConn,
                           h: RequestHandle) -> None:
        grace = max(1.0, 4 * self.poll_s)
        now = time.perf_counter()
        if h.deadline is not None:
            delay = h.deadline + grace - now
        else:
            delay = conn.last_progress + self.stream_timeout_s - now
        self._loop.call_later(max(self.poll_s, delay),
                              lambda: self._ev_check_stall(conn, h))

    def _ev_check_stall(self, conn: _ServingConn,
                        h: RequestHandle) -> None:
        """Stall watchdog: a stream with no progress past the request
        deadline (+ grace) or ``stream_timeout_s`` gets the typed
        ``"stall"`` error frame instead of pinning the relay — the
        threaded core's bounded-wait contract, on a timer instead of a
        poll loop.  Stale timers (stream already retired) no-op."""
        if conn.closed or conn.stream is not h:
            return
        grace = max(1.0, 4 * self.poll_s)
        now = time.perf_counter()
        if h.deadline is not None:
            # one empty poll slice of silence required, like the threaded
            # loop which only diagnoses a stall from an empty slice
            stalled = (now > h.deadline + grace
                       and now - conn.last_progress >= self.poll_s)
        else:
            stalled = now - conn.last_progress >= self.stream_timeout_s
        if not stalled:
            self._ev_schedule_stall(conn, h)
            return
        with self._hlock:
            self._handles.pop(h.id, None)
            self._owner.pop(h.id, None)
        self._ev_queue(conn, {"id": h.id, "ok": False, "done": True,
                              "tokens": np.zeros(0, np.int32),
                              "finish": "error", "kind": "stall",
                              "error": f"no progress on request {h.id} "
                                       f"(engine stalled)"})
        if conn.closed:
            return
        h.set_listener(None)
        conn.stream = None
        self._ev_drain_deferred(conn)

    # -- event-core write path ----------------------------------------------
    def _ev_queue(self, conn: _ServingConn, obj) -> None:
        if conn.closed:
            return
        if conn.out:
            # the pooled buffer still backs an in-flight frame: encode
            # into fresh bytes (the PS _queue_reply discipline)
            data = memoryview(networking.encode_message(obj))
        else:
            data = memoryview(networking.encode_message_into(
                obj, conn.send_pool))
        conn.out.append(data)
        conn.out_bytes += len(data)
        self._ev_flush(conn)

    def _ev_flush(self, conn: _ServingConn) -> None:
        if conn.closed:
            return
        was_paused = conn.paused
        while conn.out:
            try:
                if len(conn.out) > 1:
                    # write batching: every frame owed to this connection
                    # in ONE syscall — token chunks queued by successive
                    # pumps coalesce per loop wake
                    sent = conn.sock.sendmsg(conn.out[:_EV_SENDMSG_BATCH])
                else:
                    sent = conn.sock.send(conn.out[0])
            except (BlockingIOError, InterruptedError):
                break
            except (ConnectionError, OSError):
                self._ev_conn_lost(conn, fault=True)
                return
            conn.out_bytes -= sent
            while conn.out and sent >= len(conn.out[0]):
                sent -= len(conn.out[0])
                conn.out.pop(0)
            if sent:
                conn.out[0] = conn.out[0][sent:]
                break  # partial write: the kernel buffer is full
        self._ev_update_mask(conn)
        if was_paused and not conn.paused and not conn.closed:
            self._loop.call_soon(lambda: self._ev_resume(conn))

    def _ev_update_mask(self, conn: _ServingConn) -> None:
        if conn.closed:
            return
        if conn.paused:
            if conn.out_bytes <= self.max_conn_buffer // 2:
                conn.paused = False  # drained: resume reads + pump
        elif conn.out_bytes > self.max_conn_buffer:
            conn.paused = True  # never-reading client: stop reading too
        want = bool(conn.out)
        conn.want_write = want
        mask = ((0 if conn.paused else selectors.EVENT_READ)
                | (selectors.EVENT_WRITE if want else 0))
        if not mask:  # unreachable (paused implies pending writes), but
            mask = selectors.EVENT_READ  # a 0 mask would be an error
        self._loop.set_mask(conn.sock, mask)

    def _ev_resume(self, conn: _ServingConn) -> None:
        """Backpressure release: re-pump the stream (tokens queued while
        paused sit in the handle — bounded by its ``num_steps``), then
        re-drain parsed/deferred ops before going back to the socket."""
        if conn.closed or conn.paused:
            return
        if conn.stream is not None:
            self._ev_pump(conn, conn.stream)
        if conn.closed or conn.paused:
            return
        if conn.stream is None:
            self._ev_drain_deferred(conn)
        if not conn.closed and not conn.paused:
            self._ev_read(conn)

    # -- event-core teardown -------------------------------------------------
    def _ev_conn_lost(self, conn: _ServingConn, fault: bool) -> None:
        """Transport-level death.  Counting mirrors the threaded core:
        mid-stream death is ``_poll_client``'s "dead" verdict (cancel the
        streamed request, no counter); outside a stream a torn frame or
        send fault counts ``disconnects``; a clean EOF counts nothing."""
        if conn.closed:
            return
        h = conn.stream
        if h is not None:
            if self.cancel_on_disconnect:
                self.engine.cancel(h)
        elif fault:
            self.disconnects += 1
        self._ev_close(conn)

    def _ev_close(self, conn: _ServingConn) -> None:
        if conn.closed:
            return
        conn.closed = True
        h = conn.stream
        conn.stream = None
        if h is not None:
            h.set_listener(None)
        if self._loop is not None:
            self._loop.remove(conn.sock)
        self._econns.pop(conn.sock, None)
        try:
            conn.sock.close()
        except OSError:
            pass
        with self._lock:
            if conn.sock in self._conns:
                self._conns.remove(conn.sock)
        del conn.out[:]
        conn.out_bytes = 0
        del conn.deferred[:]
        self._release_owned(conn.sock)

    def _ev_shutdown(self) -> None:
        """Loop-exit hook (runs ON the loop thread, before the selector
        and waker close): flush pending writes bounded-best-effort, close
        every registered connection, reclaim their owned requests, close
        the listener.  ``stop(join_timeout)`` drains through here — zero
        leaked fds (tests/test_serving_event.py)."""
        conns = list(self._econns.values())
        self._econns.clear()
        with self._lock:
            self._conns.clear()
        for conn in conns:
            if conn.out:
                try:
                    conn.sock.setblocking(True)
                    conn.sock.settimeout(0.5)
                    for buf in conn.out:
                        conn.sock.sendall(buf)
                except (ConnectionError, OSError, socket.timeout):
                    pass
            h = conn.stream
            conn.stream = None
            if h is not None:
                h.set_listener(None)
            conn.closed = True
            try:
                conn.sock.close()
            except OSError:
                pass
            self._release_owned(conn.sock)
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass


def _raise_typed(kind: Optional[str], err: str):
    """Map a typed error reply back to the exception the engine raised."""
    if kind == "quota":  # before backpressure: QuotaExceeded IS a QueueFull
        raise QuotaExceeded(err)
    if kind == "backpressure" or "queue full" in err:
        raise QueueFull(err)
    if kind == "draining":
        raise Draining(err)
    if kind in ("engine_dead", "stall"):
        raise EngineDead(err)
    raise ValueError(err)


class ServingClient:
    """Minimal client for :class:`ServingServer` — one socket, the shared
    frame codec, pooled receives.  ``generate`` is the one-call form whose
    returned row matches offline ``generate`` for the same request; with a
    ``retry_policy`` (``resilience.RetryPolicy``) it re-dials and
    resubmits across engine deaths and connection resets — requests are
    deterministic in their seed, so the retry is idempotent."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, int(port)
        self.sock = networking.connect(self.host, self.port)
        self._pool = networking.BufferPool()
        self._send_pool = networking.BufferPool()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _redial(self) -> None:
        self.close()
        self.sock = networking.connect(self.host, self.port)

    def __enter__(self) -> "ServingClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def submit(self, prompt, num_steps: int, **kw) -> int:
        """Enqueue a request; returns the server-assigned id.  Raises the
        typed rejection: :class:`QueueFull` (backpressure),
        :class:`Draining`, :class:`EngineDead`, or ``ValueError``."""
        req = {"prompt": np.asarray(prompt, np.int32),
               "num_steps": int(num_steps), **kw}
        networking.send_opcode(self.sock, OP_ENQUEUE)
        networking.send_data(self.sock, req, pool=self._send_pool)
        ack = networking.recv_data(self.sock, pool=self._pool)
        if not ack.get("ok"):
            _raise_typed(ack.get("kind"), str(ack.get("error", "rejected")))
        return int(ack["id"])

    def submit_prefilled(self, blocks, prompt, first_token: int,
                         num_steps: int, **kw) -> int:
        """Ship a prefilled request's KV blocks to a decode-role server
        (``SERVING_OP_KVBLOCKS``) — the wire half of the disaggregated
        hand-off.  ``blocks`` is a :class:`networking.KVBlocks`; the block
        payloads ride the frame codec's zero-copy buffer path.  Returns
        the server-assigned id; raises the same typed rejections as
        :meth:`submit`."""
        req = {"blocks": blocks,
               "prompt": np.asarray(prompt, np.int32),
               "first_token": int(first_token),
               "num_steps": int(num_steps), **kw}
        networking.send_opcode(self.sock, OP_KVBLOCKS)
        networking.send_data(self.sock, req, pool=self._send_pool)
        ack = networking.recv_data(self.sock, pool=self._pool)
        if not ack.get("ok"):
            _raise_typed(ack.get("kind"), str(ack.get("error", "rejected")))
        return int(ack["id"])

    def cancel(self, rid: int, await_ack: bool = True) -> bool:
        """Cancel request ``rid``.  With ``await_ack=False`` the cancel is
        fire-and-forget — the form to use from another thread while THIS
        socket is mid-stream (the ack would interleave with chunk frames;
        the stream's final ``finish="cancel"`` frame is the
        acknowledgement there)."""
        networking.send_opcode(self.sock, OP_CANCEL)
        networking.send_data(self.sock, {"id": int(rid)},
                             pool=self._send_pool)
        if not await_ack:
            return True
        ack = networking.recv_data(self.sock, pool=self._pool)
        return bool(ack.get("cancelled"))

    def load(self) -> Dict[str, Any]:
        """Probe the server's engine load (``SERVING_OP_STATS``): the
        lock-free :meth:`ServingEngine.load` snapshot — queue depth, free
        slots, trie-cached block count, draining/dead flags.  Cheap enough
        for a router to poll per dispatch."""
        networking.send_opcode(self.sock, OP_STATS)
        reply = networking.recv_data(self.sock, pool=self._pool)
        if not reply.get("ok"):
            _raise_typed(reply.get("kind"),
                         str(reply.get("error", "stats probe rejected")))
        return dict(reply["load"])

    def stream(self, rid: int):
        """Yield ``(tokens, done_reply)`` chunk by chunk; ``done_reply`` is
        None until the final frame (which carries ``finish`` —
        eos/length/deadline/cancel — and the padded ``row``).  Typed error
        frames raise: :class:`EngineDead` for ``engine_dead``/``stall``,
        ``ValueError`` otherwise."""
        networking.send_opcode(self.sock, OP_STREAM)
        networking.send_data(self.sock, {"id": int(rid)},
                             pool=self._send_pool)
        while True:
            reply = networking.recv_data(self.sock, pool=self._pool)
            if reply.get("error"):
                _raise_typed(reply.get("kind"), str(reply["error"]))
            tokens = np.array(reply["tokens"], np.int32, copy=True)
            if reply["done"]:
                yield tokens, {"finish": reply["finish"],
                               "row": np.array(reply["row"], np.int32,
                                               copy=True)}
                return
            yield tokens, None

    def generate(self, prompt, num_steps: int, retry_policy=None,
                 **kw) -> np.ndarray:
        """Submit + stream to completion; returns the full padded row
        (prompt + tokens), exactly ``generate``-shaped.  ``retry_policy``
        (a ``resilience.RetryPolicy``) retries the whole submit+stream on
        :class:`EngineDead` or a transport fault, re-dialing first — the
        client-side half of the supervised-restart story."""
        def attempt() -> np.ndarray:
            rid = self.submit(prompt, num_steps, **kw)
            for _, done in self.stream(rid):
                if done is not None:
                    return done["row"]
            raise ConnectionError("stream ended without a done frame")

        if retry_policy is None:
            return attempt()
        return retry_policy.call_reconnecting(
            attempt, self._redial,
            retry_on=(EngineDead, ConnectionError, OSError))


# ---------------------------------------------------------------------------
# disaggregated prefill/decode (PR 16)
# ---------------------------------------------------------------------------

class _DisaggRequest:
    """One in-flight request's routing record inside a :class:`DisaggPair`:
    the client-facing proxy handle, the current upstream handle it mirrors
    (prefill first, decode after the hand-off), and a cancel relay that
    always points at whichever engine owns the upstream right now."""

    __slots__ = ("proxy", "upstream", "cancel_fn", "cancelled", "thread",
                 "kw", "attempts")

    def __init__(self, proxy: RequestHandle, kw: Optional[Dict[str, Any]]
                 = None):
        self.proxy = proxy
        self.upstream: Optional[RequestHandle] = None
        self.cancel_fn = None
        self.cancelled = False
        self.thread: Optional[threading.Thread] = None
        self.kw: Dict[str, Any] = dict(kw or {})
        self.attempts = 1  # prefill admissions so far (re-route budget)


class DisaggPair:
    """Disaggregated serving: N ``role="prefill"`` engines feeding ONE
    ``role="decode"`` engine, behind the unified engine's client surface
    (``submit`` → :class:`RequestHandle` → ``next_chunk``/``result``).

    Admissions route to a prefill engine (round-robin); when its half
    retires (``finish="prefilled"``), the request's filled KV blocks ship
    to the decode engine — in-process via ``submit_prefilled`` when
    ``decode`` is an engine, or over the serving wire
    (``SERVING_OP_KVBLOCKS`` through :class:`ServingClient`) when
    ``decode_addr`` names a remote decode-role :class:`ServingServer`.
    The client-visible stream is unchanged: tokens relay into the proxy
    handle as the decode engine emits them, and greedy output is
    token-identical to a unified engine (the decode engine resumes from
    bit-exact shipped KV at the shipped position with the same RNG key).

    Failure matrix (docs/serving.md):

     - **prefill death** mid-prefill or mid-transfer re-routes: the
       request resubmits to the next live prefill engine with its
       ORIGINAL rng key (deterministic, so the retry is idempotent),
       bounded by one attempt per engine; blocks the dead engine held are
       reclaimed by its own death path, and the decode pool never saw the
       torn transfer (``kv_blocks_in_use == 0`` on both sides).
     - **decode death** is terminal: the proxy fails with the typed
       :class:`EngineDead` (no silent re-route — the decode engine owns
       all live KV state, exactly the supervised-restart seam
       ``resilience.PairSupervisor`` covers).
     - **cancel/deadline** land on whichever engine currently owns the
       request; the proxy mirrors the upstream finish reason.
    """

    def __init__(self, prefills, decode: Optional[ServingEngine] = None,
                 decode_addr: Optional[Tuple[str, int]] = None,
                 poll_s: float = 0.02):
        if isinstance(prefills, ServingEngine):
            prefills = [prefills]
        if not prefills:
            raise ValueError("DisaggPair needs at least one prefill engine")
        for e in prefills:
            if e.role != "prefill":
                raise ValueError(f"prefill engines must be role='prefill', "
                                 f"got role={e.role!r}")
        if (decode is None) == (decode_addr is None):
            raise ValueError("pass exactly one of decode= (in-process "
                             "engine) or decode_addr= (remote server)")
        if decode is not None and decode.role != "decode":
            raise ValueError(f"decode engine must be role='decode', got "
                             f"role={decode.role!r}")
        self._prefills: List[ServingEngine] = list(prefills)
        self._decode = decode
        self._decode_addr = decode_addr
        self.poll_s = float(poll_s)
        self._lock = threading.Lock()
        self._live: Dict[int, _DisaggRequest] = {}
        self._next_id = 0
        self._rr = 0  # round-robin cursor over prefill engines
        #: shared event relay (PR 19): ONE loop watches every in-flight
        #: request across both halves — prefill completion, the KV
        #: hand-off, and the decode token relay — instead of a routing
        #: thread per request.  Lazily started on first submit.
        self._relay_loop: Optional[networking.EventLoop] = None
        # the pair's OWN terminal accounting: engine counters double-count
        # a re-routed request (every attempt is a submission somewhere), so
        # client-facing totals live here
        self.counters: Dict[str, int] = {
            "requests_submitted": 0, "requests_completed": 0,
            "requests_failed": 0, "requests_rejected": 0,
            "requests_cancelled": 0, "requests_expired": 0,
            "prefill_reroutes": 0,
        }

    # ------------------------------------------------------------ lifecycle
    def warmup(self) -> "DisaggPair":
        """Compile every engine's role-specific programs (prefill buckets
        + gather on the prefill side, decode step + ingest on the decode
        side) before traffic — the pair-level twin of
        ``ServingEngine.warmup``."""
        for e in self.engines:
            e.warmup()
        return self

    def start(self) -> "DisaggPair":
        for e in self.engines:  # prefill engines first, then decode
            e.start()
        return self

    def stop(self, join_timeout: float = 10.0) -> None:
        for e in self.engines:
            e.stop(join_timeout=join_timeout)
        self._ev_wait_idle(join_timeout)
        with self._lock:
            loop, self._relay_loop = self._relay_loop, None
        if loop is not None:
            loop.stop(join_timeout=join_timeout)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain, prefill side first (no new hand-offs) then the
        decode engine; the event relay pumps the final laps out so every
        proxy reaches a terminal state before this returns."""
        with self._lock:
            pres, dec = list(self._prefills), self._decode
        clean = all([e.drain(timeout=timeout) for e in pres])
        if dec is not None:
            clean = dec.drain(timeout=timeout) and clean
        self._ev_wait_idle(5.0)
        return clean

    def __enter__(self) -> "DisaggPair":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------ admission
    def submit(self, prompt, num_steps: int, **kw) -> RequestHandle:
        """Unified-engine ``submit`` surface.  Returns a proxy handle whose
        stream spans both halves: TTFT is the prefill engine's first
        token, every later token is the decode engine's."""
        prompt = np.asarray(prompt, np.int32)
        ph, eng = self._submit_prefill(prompt, num_steps, kw, first=True)
        if num_steps == 0:
            # the prefill engine completed it in place ("empty"): nothing
            # to hand off, and the engine's own counters saw it — mirror
            # into the pair's
            with self._lock:
                self.counters["requests_submitted"] += 1
                self.counters["requests_completed"] += 1
            return ph
        with self._lock:
            self._next_id += 1
            proxy = RequestHandle(
                self._next_id, prompt, num_steps,
                float(kw.get("temperature", 0.0)), kw.get("top_k"),
                kw.get("top_p"), kw.get("eos_id"), kw.get("pad_id"),
                ph.key, deadline_s=kw.get("deadline_s"))
            rec = _DisaggRequest(proxy, kw)
            rec.upstream = ph
            rec.cancel_fn = (lambda e=eng, h=ph: e.cancel(h))
            self._live[proxy.id] = rec
            self.counters["requests_submitted"] += 1
        self._ev_watch_prefill(rec, ph)
        return proxy

    def _submit_prefill(self, prompt, num_steps, kw, first: bool,
                        rng=None):
        """Round-robin submit over LIVE prefill engines; on the first
        admission typed backpressure propagates to the caller after every
        engine refused, on a re-route the caller handles it."""
        last: Optional[BaseException] = None
        with self._lock:
            attempts_budget = len(self._prefills)
        for _ in range(attempts_budget):
            with self._lock:
                eng = self._prefills[self._rr % len(self._prefills)]
                self._rr += 1
            try:
                sub = dict(kw)
                # pair admission is non-blocking by construction: a full
                # prefill queue tries the next engine instead of parking
                sub.pop("block", None)
                sub.pop("timeout", None)
                if rng is not None:
                    sub.pop("seed", None)
                    sub["rng"] = rng
                return eng.submit(prompt, num_steps, block=False,
                                  **sub), eng
            except (EngineDead, QueueFull, Draining) as e:
                last = e
        if first:
            with self._lock:
                self.counters["requests_rejected"] += 1
        raise last if last is not None else EngineDead(
            "no live prefill engine")

    # -------------------------------------------------------------- routing
    #
    # The whole request lifecycle rides the pair's shared event loop
    # (PR 19): the prefill handle's listener wakes the loop when its half
    # retires, the KV hand-off runs as a loop callback (non-blocking
    # decode admission, with a ``call_later`` retry while the decode
    # queue is full), and the decode half relays listener-driven — no
    # per-request routing thread anywhere on the path.

    def _ev_loop(self) -> "networking.EventLoop":
        with self._lock:
            loop = self._relay_loop
            if loop is None or not loop.alive:
                loop = networking.EventLoop(name="dkt-disagg-relay")
                loop.start()
                self._relay_loop = loop
            return loop

    def _ev_watch_prefill(self, rec: _DisaggRequest,
                          ph: RequestHandle) -> None:
        loop = self._ev_loop()
        ph.set_listener(lambda: loop.call_soon(
            lambda: self._ev_prefill_done(rec, ph)))
        loop.call_soon(lambda: self._ev_prefill_done(rec, ph))

    def _ev_prefill_done(self, rec: _DisaggRequest,
                         ph: RequestHandle) -> None:
        """Loop-side prefill watcher: when the prefill half retires, hand
        off (``finish="prefilled"``), re-route a death with the ORIGINAL
        key (bit-identical retry, bounded by one attempt per engine), or
        mirror a cancel/deadline/drain finish."""
        proxy = rec.proxy
        if rec.upstream is not ph or not ph.done:
            return  # stale wake, or woken by a token push mid-prefill
        ph.set_listener(None)
        rec.upstream = None  # claim the transition exactly once
        if ph.finish == "prefilled":
            kvb = ph.kvblocks
            first_token = int(ph.tokens[0])
            with self._lock:
                dec = self._decode  # in-flight hand-offs keep their engine
            if dec is not None:
                self._ev_handoff_local(rec, kvb, first_token, dec)
            else:
                self._ev_handoff_wire(rec, kvb, first_token)
            return
        if ph.error is not None:
            # prefill engine died with the request in flight: re-route
            # with the ORIGINAL key so the retry is bit-identical
            with self._lock:
                budget = len(self._prefills) + 1
            if rec.attempts >= budget:
                self._retire(rec, error=EngineDead(
                    f"request {proxy.id}: every prefill re-route "
                    f"failed ({ph.error})"))
                return
            with self._lock:
                self.counters["prefill_reroutes"] += 1
                cancelled = rec.cancelled
            if cancelled:
                self._retire(rec, finish="cancel")
                return
            try:
                nph, eng = self._submit_prefill(
                    proxy.prompt, proxy.num_steps, rec.kw, first=False,
                    rng=proxy.key)
            except (EngineDead, QueueFull, Draining) as e:
                self._retire(rec, error=e)
                return
            with self._lock:
                rec.upstream = nph
                rec.cancel_fn = (lambda e=eng, h=nph: e.cancel(h))
                if rec.cancelled:
                    rec.cancel_fn()
            rec.attempts += 1
            self._ev_watch_prefill(rec, nph)
            return
        # cancel / deadline / drain on the prefill half: mirror it
        self._retire(rec, finish=ph.finish)

    def _ev_handoff_local(self, rec: _DisaggRequest, kvb,
                          first_token: int, dec: ServingEngine) -> None:
        """In-process hand-off on the loop: non-blocking decode admission,
        re-armed via ``call_later`` while the decode queue is full (the
        event-core analogue of the old thread's ``block=True`` park)."""
        proxy = rec.proxy
        if rec.cancelled:
            self._retire(rec, finish="cancel")
            return
        try:
            dh = dec.submit_prefilled(
                kvb, proxy.prompt, first_token, proxy.num_steps,
                temperature=proxy.temperature, top_k=proxy.top_k,
                top_p=proxy.top_p, eos_id=proxy.eos_id,
                pad_id=proxy.pad_id, deadline_s=rec.kw.get("deadline_s"),
                block=False)
        except QueueFull:
            self._relay_loop.call_later(
                self.poll_s, lambda: self._ev_handoff_local(
                    rec, kvb, first_token, dec))
            return
        except (EngineDead, Draining) as e:
            # decode death is terminal (typed), never silently re-routed:
            # the decode engine owns all live KV state
            self._retire(rec, error=e)
            return
        except ValueError as e:
            self._retire(rec, error=e)
            return
        with self._lock:
            rec.upstream = dh
            rec.cancel_fn = (lambda e=dec, h=dh: e.cancel(h))
            if rec.cancelled:
                rec.cancel_fn()
        loop = self._relay_loop
        dh.set_listener(lambda: loop.call_soon(
            lambda: self._ev_pump_decode(rec, dh)))
        self._ev_pump_decode(rec, dh)

    def _ev_pump_decode(self, rec: _DisaggRequest,
                        dh: RequestHandle) -> None:
        """Loop-side decode relay: drain ready chunks into the proxy."""
        if rec.upstream is not dh:
            return  # stale wake
        proxy = rec.proxy
        while True:
            chunk, done = dh.next_chunk(timeout=0)
            for t in chunk:
                proxy._push(int(t))
            if done:
                dh.set_listener(None)
                rec.upstream = None
                if dh.error is not None:
                    self._retire(rec, error=dh.error)
                else:
                    self._retire(rec, finish=dh.finish)
                return
            if not len(chunk):
                return  # drained; the listener wakes us on more

    def _ev_handoff_wire(self, rec: _DisaggRequest, kvb,
                         first_token: int) -> None:
        """Wire hand-off on the loop: ship the block set to the remote
        decode server (``SERVING_OP_KVBLOCKS``), then relay its reply
        stream non-blocking off a bare-frame parser."""
        proxy = rec.proxy
        client = ServingClient(*self._decode_addr)
        try:
            rid = client.submit_prefilled(
                kvb, proxy.prompt, first_token, proxy.num_steps,
                temperature=proxy.temperature, top_k=proxy.top_k,
                top_p=proxy.top_p, eos_id=proxy.eos_id,
                pad_id=proxy.pad_id, deadline_s=rec.kw.get("deadline_s"))
            networking.send_opcode(client.sock, OP_STREAM)
            networking.send_data(client.sock, {"id": int(rid)},
                                 pool=client._send_pool)
            client.sock.setblocking(False)
        except (EngineDead, ConnectionError, OSError) as e:
            client.close()
            self._retire(rec, error=e if isinstance(e, EngineDead)
                         else EngineDead(f"decode engine unreachable: "
                                         f"{e!r}"))
            return
        except ValueError as e:
            client.close()
            self._retire(rec, error=e)
            return
        with self._lock:
            rec.cancel_fn = (lambda c=client, r=rid:
                             c.cancel(r, await_ack=False))
            if rec.cancelled:
                try:
                    rec.cancel_fn()
                except (ConnectionError, OSError):
                    pass
        parser = networking.FrameParser(frame_ops=None)
        scratch = networking.BufferPool()
        loop = self._relay_loop
        if loop is None:
            client.close()
            return
        loop.add(client.sock,
                 lambda mask: self._ev_wire_read(rec, client, parser,
                                                 scratch))

    def _ev_wire_read(self, rec: _DisaggRequest, client, parser,
                      scratch) -> None:
        sock = client.sock
        while True:
            target = parser.writable()
            fed_scratch = target is None
            if fed_scratch:
                target = memoryview(scratch.get(_EV_RECV_CHUNK))
            try:
                n = sock.recv_into(target)
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionError, OSError) as e:
                self._ev_wire_lost(rec, client, e)
                return
            if not n:
                self._ev_wire_lost(rec, client,
                                   ConnectionError("stream ended without "
                                                   "a done frame"))
                return
            if fed_scratch:
                parser.feed(target[:n])
            else:
                parser.advance(n)
            try:
                for _op, msg in parser.messages():
                    if self._ev_wire_frame(rec, client, msg):
                        return  # stream finished / typed failure
            except ValueError as e:
                self._ev_wire_lost(rec, client, e)
                return

    def _ev_wire_frame(self, rec: _DisaggRequest, client, msg) -> bool:
        """One decode-server reply frame.  Returns True when the stream
        detached (done or failed) — decode death is terminal, typed."""
        if msg.get("error"):
            kind = msg.get("kind")
            err = str(msg["error"])
            self._ev_wire_detach(rec, client)
            if kind in ("engine_dead", "stall"):
                self._retire(rec, error=EngineDead(err))
            else:
                self._retire(rec, error=ValueError(err))
            return True
        for t in msg["tokens"]:
            rec.proxy._push(int(t))
        if msg["done"]:
            self._ev_wire_detach(rec, client)
            self._retire(rec, finish=msg["finish"])
            return True
        return False

    def _ev_wire_detach(self, rec: _DisaggRequest, client) -> None:
        loop = self._relay_loop
        if loop is not None:
            loop.remove(client.sock)
        client.close()

    def _ev_wire_lost(self, rec: _DisaggRequest, client,
                      err: BaseException) -> None:
        self._ev_wire_detach(rec, client)
        self._retire(rec, error=err if isinstance(err, EngineDead)
                     else EngineDead(f"decode engine unreachable: "
                                     f"{err!r}"))

    def _ev_wait_idle(self, timeout: float) -> None:
        """Bounded wait for the loop to retire the in-flight requests —
        stopping/draining the engines makes their handles terminal, and
        the loop pumps those final laps out asynchronously."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                busy = bool(self._live)
            if not busy or time.monotonic() >= deadline:
                return
            time.sleep(0.005)

    def _retire(self, rec: _DisaggRequest, finish: Optional[str] = None,
                error: Optional[BaseException] = None) -> None:
        """Make the proxy terminal exactly once and book the pair-level
        counter for its reason."""
        proxy = rec.proxy
        if error is not None:
            exc = (error if isinstance(error, EngineDead)
                   else EngineDead(str(error)))
            counted = proxy._fail(exc)
            key = "requests_failed"
        else:
            counted = proxy._finish(finish)
            key = {"cancel": "requests_cancelled",
                   "deadline": "requests_expired"}.get(
                       finish, "requests_completed")
        with self._lock:
            if counted:
                self.counters[key] += 1
            self._live.pop(proxy.id, None)

    # ------------------------------------------------------------- controls
    def cancel(self, handle: RequestHandle) -> bool:
        """Cancel a proxy handle wherever its request currently lives
        (queued/prefilling, mid-transfer, or decoding).  Returns False if
        it already finished."""
        with handle._cond:
            if handle.finish is not None:
                return False
        with self._lock:
            rec = self._live.get(handle.id)
            if rec is None or rec.proxy is not handle:
                return False
            rec.cancelled = True
            fn = rec.cancel_fn
        if fn is not None:
            try:
                fn()
            except (ConnectionError, OSError):
                pass  # upstream gone: its death path retires the proxy
        return True

    def replace_engine(self, old: ServingEngine,
                       new: ServingEngine) -> None:
        """Swap a respawned engine into the pair (the
        ``resilience.PairSupervisor`` restart seam).  In-flight requests
        on the old engine fail through its death path and re-route."""
        with self._lock:
            for i, e in enumerate(self._prefills):
                if e is old:
                    self._prefills[i] = new
                    return
            if self._decode is old:
                self._decode = new
                return
        raise ValueError("engine to replace is not part of this pair")

    # ------------------------------------------------------------ telemetry
    @property
    def engines(self) -> List[ServingEngine]:
        with self._lock:
            return self._prefills + ([self._decode]
                                     if self._decode is not None else [])

    @property
    def stats(self) -> Dict[str, Any]:
        """Merged engine stats (numeric counters summed, sample lists
        concatenated) with the request-level terminal counters OVERRIDDEN
        by the pair's own: a re-routed request is one client request, not
        one per attempt."""
        merged: Dict[str, Any] = {}
        for e in self.engines:
            for k, v in e.stats.items():
                if isinstance(v, bool) or not isinstance(
                        v, (int, float, list)):
                    merged.setdefault(k, v)
                elif isinstance(v, list):
                    merged.setdefault(k, [])
                    merged[k] = merged[k] + list(v)
                else:
                    merged[k] = merged.get(k, 0) + v
        with self._lock:
            merged.update(self.counters)
        return merged

    @property
    def kv_blocks_in_use(self) -> Optional[int]:
        """Sum across BOTH sides — the zero-leak assertion surface."""
        vals = [e.kv_blocks_in_use for e in self.engines]
        vals = [v for v in vals if v is not None]
        return sum(vals) if vals else None

    @property
    def slot_occupancy(self) -> Optional[float]:
        """The DECODE engine's occupancy (None for wire-mode pairs): the
        continuous-batching health metric disaggregation exists to
        protect."""
        with self._lock:
            dec = self._decode
        return dec.slot_occupancy if dec is not None else None

    @property
    def max_len(self) -> int:
        return min(e.max_len for e in self.engines)

    @property
    def queue_depth(self) -> int:
        return sum(e.queue_depth for e in self.engines)

    @property
    def dead(self) -> Optional[BaseException]:
        """The first dead engine's error, or None while every engine in
        the pair is live."""
        for e in self.engines:
            if e.dead is not None:
                return e.dead
        return None
