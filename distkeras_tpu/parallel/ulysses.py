"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

No reference counterpart (SURVEY.md §2.3: sequence parallelism absent
upstream); this is the second of the two SP schedules SURVEY §5 names
("ring attention or all-to-all sequence/context parallelism").  Where
``ring.py`` keeps q resident and rotates k/v around the ICI ring in
``sp`` steps, the all-to-all schedule pays exactly TWO collectives total:

    (B, S/sp, H, Dh)  --all_to_all-->  (B, S, H/sp, Dh)
        attend locally over the FULL sequence (flash kernel eligible)
    (B, S, H/sp, Dh)  --all_to_all-->  (B, S/sp, H, Dh)

Each device ends up owning ``H/sp`` whole heads over the whole sequence,
computes ordinary (causal/windowed) attention for them — on TPU that local
attend dispatches to the Pallas flash kernel, which the ring's hand-rolled
online-softmax rotation cannot use — and reshards back.  Trade-offs vs the
ring, so callers can pick per workload:

  * collectives: 2 all_to_alls (each moves the full q/k/v+out bytes once)
    vs ``sp`` ppermutes of the k/v shard (k/v bytes ``sp`` times);
  * overlap: the ring overlaps transfer with compute (double-buffered);
    all_to_all is a barrier — but only two of them;
  * memory: full-S keys live on each device during the attend (score
    blocks stay flash-bounded), so the ring remains the choice when even
    one head's full-S kv does not fit;
  * constraint: the head count (q AND kv) must divide by ``sp``; the ring
    has no head-count requirement.

The sequence blocks land in device order along the axis (``tiled``
all_to_all concatenates by axis index), matching the contiguous-block
sharding the transformer uses, so global causal/window masks and
pre-applied RoPE rotations line up unchanged.
"""

from __future__ import annotations

from typing import Optional

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat

from .ring import SEQ_AXIS


def ulysses_attention(q, k, v, axis_name: str = SEQ_AXIS,
                      causal: bool = False, scale: Optional[float] = None,
                      window: Optional[int] = None):
    """All-to-all sequence-parallel attention — call *inside* shard_map.

    q: local shard (B, S_local, H, Dh); k, v: (B, S_local, Hkv, Dh) with
    Hkv | H (grouped-query attention).  Sequence-sharded on ``axis_name``
    in contiguous blocks; returns the local (B, S_local, H, Dh) output in
    q.dtype.

    Head divisibility: requires ``H % sp == 0``.  When ``Hkv % sp != 0``
    each k/v head is first repeated ``sp/gcd(Hkv, sp)`` times — the
    smallest expansion making the kv head count (``lcm(Hkv, sp)``)
    splittable — since the GQA grouping cannot be split mid-group across
    devices; the repeat costs all_to_all payload, so keep ``num_kv_heads``
    a multiple of the seq-axis size where the cache/propagation savings
    matter.  Head-group alignment: device j's q slice [j·H/sp, (j+1)·H/sp)
    consumes exactly kv slice [j·Hkv/sp, (j+1)·Hkv/sp) whenever
    ``Hkv % sp == 0`` (which the repeat establishes), so the per-device
    GQA ratio equals the global one and the grouped attend is unchanged.
    """
    from ..ops.attention import attention

    sp = _compat.axis_size(axis_name)
    b, s_loc, h, dh = q.shape
    hkv = k.shape[2]
    if h % sp:
        raise ValueError(
            f"ulysses attention needs num_heads % seq-axis size == 0, got "
            f"{h} heads over sp={sp} (use the ring schedule otherwise)")
    if hkv % sp:
        r = sp // math.gcd(hkv, sp)
        k = jnp.repeat(k, r, axis=2)
        v = jnp.repeat(v, r, axis=2)

    def to_heads(x):
        # (B, S/sp, H', Dh) -> (B, S, H'/sp, Dh): split heads, gather seq
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    # full sequence resident: the ordinary dispatcher applies (Pallas flash
    # on TPU when shapes qualify, XLA reference otherwise); global causal /
    # sliding-window semantics need no position bookkeeping here
    out = attention(q, k, v, causal=causal, scale=scale, window=window)
    # (B, S, H/sp, Dh) -> (B, S/sp, H, Dh): split seq, gather heads
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           window: Optional[int] = None):
    """Convenience wrapper: global (B, S, H, Dh) arrays in, sequence sharded
    over ``mesh[axis_name]``, all-to-all attention, global array out.  For
    models already running under shard_map, call ``ulysses_attention``
    directly (same shape as ``ring.ring_self_attention``)."""
    spec = P(None, axis_name, None, None)
    fn = _compat.shard_map(
        lambda a, b_, c: ulysses_attention(a, b_, c, axis_name, causal,
                                           scale, window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sharding), jax.device_put(k, sharding),
              jax.device_put(v, sharding))
