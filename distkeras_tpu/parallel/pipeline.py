"""Pipeline parallelism — GPipe-style microbatch pipeline over a mesh axis.

No reference counterpart (SURVEY.md §2.3: pipeline parallelism absent
upstream).  Layers are partitioned into ``n`` stages, one stage's params per
device on the 'stage' mesh axis; microbatches stream through the ring with
``lax.ppermute`` carrying activations stage→stage each tick.  The schedule
runs ``M + n - 1`` ticks (M microbatches + the fill/drain bubble); every
device executes the *same* program every tick (SPMD uniformity — bubbles
compute on garbage and their results are masked out), and reverse-mode
autodiff through the scan + ppermute gives pipeline-parallel backprop for
free (ppermute's transpose is the reverse permute).

Constraint: the stage function must be shape-preserving ((micro_b, ...) →
same shape), which holds for transformer blocks — the canonical PP workload.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

STAGE_AXIS = "stage"


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis_name: str = STAGE_AXIS):
    """Run microbatches through the stage pipeline — call inside shard_map.

    stage_fn(params, x) -> y, shape-preserving.
    stage_params: this device's stage params (leading 'stage' axis already
    split by shard_map, squeezed by the caller).
    x_micro: (M, micro_b, ...) microbatches — meaningful on stage 0 (other
    stages may carry zeros; their values are ignored).
    Returns (M, micro_b, ...): meaningful on the last stage, zeros elsewhere.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = x_micro.shape[0]
    ticks = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]  # send forward
    micro_shape = x_micro.shape[1:]

    # carry zeros derive from x_micro so they inherit its varying axes (e.g.
    # 'data' when the pipeline composes with data parallelism inside one
    # shard_map), plus the stage axis the ring introduces.  stage_fn must
    # not make its output vary over further mesh axes beyond these.
    varying = lambda a: jax.lax.pcast(a, axis_name, to="varying")
    buf0 = varying(jnp.zeros_like(x_micro[0]))
    out0 = varying(jnp.zeros_like(x_micro, jnp.float32))

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t (clamped during drain); later stages
        # consume what arrived from the previous stage last tick
        feed = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, m - 1), keepdims=False)
        x_in = jnp.where(idx == 0, feed, buf)
        y = stage_fn(stage_params, x_in)
        # the last stage finished microbatch t-(n-1); record it (masked to
        # zero elsewhere and during fill)
        slot = t - (n - 1)
        record = jnp.where((idx == n - 1) & (slot >= 0),
                           y.astype(jnp.float32),
                           jnp.zeros_like(y, jnp.float32))
        # during fill (slot < 0) this writes zeros into slot 0, which the
        # real slot-0 record overwrites at tick n-1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, record, jnp.clip(slot, 0, m - 1), axis=0)
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    return outputs
