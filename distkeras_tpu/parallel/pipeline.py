"""Pipeline parallelism — GPipe-style microbatch pipeline over a mesh axis.

No reference counterpart (SURVEY.md §2.3: pipeline parallelism absent
upstream).  Layers are partitioned into ``n`` stages, one stage's params per
device on the 'stage' mesh axis; microbatches stream through the ring with
``lax.ppermute`` carrying activations stage→stage each tick.  The schedule
runs ``M + n - 1`` ticks (M microbatches + the fill/drain bubble); every
device executes the *same* program every tick (SPMD uniformity — bubbles
compute on garbage and their results are masked out), and reverse-mode
autodiff through the scan + ppermute gives pipeline-parallel backprop for
free (ppermute's transpose is the reverse permute).

Constraint: the stage function must be shape-preserving ((micro_b, ...) →
same shape), which holds for transformer blocks — the canonical PP workload.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from . import _compat

tmap = jax.tree_util.tree_map

STAGE_AXIS = "stage"


def pipeline_apply(stage_fn: Callable, stage_params, x_micro,
                   axis_name: str = STAGE_AXIS):
    """Run microbatches through the stage pipeline — call inside shard_map.

    stage_fn(params, x) -> y, shape-preserving.
    stage_params: this device's stage params (leading 'stage' axis already
    split by shard_map, squeezed by the caller).
    x_micro: (M, micro_b, ...) microbatches — meaningful on stage 0 (other
    stages may carry zeros; their values are ignored).
    Returns (M, micro_b, ...): meaningful on the last stage, zeros elsewhere.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m = x_micro.shape[0]
    ticks = m + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]  # send forward
    micro_shape = x_micro.shape[1:]

    # carry zeros derive from x_micro so they inherit its varying axes (e.g.
    # 'data' when the pipeline composes with data parallelism inside one
    # shard_map), plus the stage axis the ring introduces.  stage_fn must
    # not make its output vary over further mesh axes beyond these.
    varying = lambda a: _compat.pcast(a, axis_name, to="varying")
    buf0 = varying(jnp.zeros_like(x_micro[0]))
    out0 = varying(jnp.zeros_like(x_micro, jnp.float32))

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t (clamped during drain); later stages
        # consume what arrived from the previous stage last tick
        feed = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, m - 1), keepdims=False)
        x_in = jnp.where(idx == 0, feed, buf)
        y = stage_fn(stage_params, x_in)
        # the last stage finished microbatch t-(n-1); record it (masked to
        # zero elsewhere and during fill)
        slot = t - (n - 1)
        record = jnp.where((idx == n - 1) & (slot >= 0),
                           y.astype(jnp.float32),
                           jnp.zeros_like(y, jnp.float32))
        # during fill (slot < 0) this writes zeros into slot 0, which the
        # real slot-0 record overwrites at tick n-1
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, record, jnp.clip(slot, 0, m - 1), axis=0)
        buf = jax.lax.ppermute(y, axis_name, perm)
        return (buf, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    return outputs


def pipeline_1f1b(stage_fn, stage_params, x_micro, labels_micro,
                  head_loss_fn, head_params, axis_name: str = STAGE_AXIS):
    """One-forward-one-backward pipeline TRAIN step — call inside shard_map.

    GPipe's backward (reverse-mode autodiff through ``pipeline_apply``'s
    scan) runs all M forwards before any backward, so every stage holds
    O(M) microbatch activations when the backward starts.  This schedule
    interleaves them: the last stage back-propagates microbatch m in the
    same tick it finishes m's forward, cotangents flow back through a
    second (reverse) ppermute ring while later microbatches are still
    flowing forward, and each stage stores only a rotating buffer of
    ``2n - 1`` microbatch *inputs* (re-linearized at backward time,
    remat-style) — activation memory O(n), independent of M.

    The whole backward is built by hand from per-stage ``jax.vjp`` calls:
    no outer ``jax.grad`` is involved, the returned cotangents ARE the
    gradients.  Per SPMD uniformity every stage computes a forward, a head
    loss and a backward every tick; bubble ticks work on garbage and their
    contributions are masked to zero (finite garbage — buffers start at
    zero and ``stage_fn`` keeps them finite).

    Schedule (0-based tick t, stage s, n stages, M microbatches):
      forward of m on s  at t = s + m
      backward of m on s at t = 2(n-1) - s + m
    so the last stage's backward of m lands in the same tick as its
    forward, and the total tick count is ``M + 2(n-1)`` with the same
    2(n-1)-tick fill/drain bubble as GPipe fwd+bwd.

    Arguments
    ---------
    stage_fn(params, x) -> y: shape-preserving stage program.
    stage_params: this stage's param slice (already squeezed).
    x_micro: (M, micro_b, ...) stage-0 inputs (embedded tokens).
    labels_micro: (M, micro_b, S) labels, consumed by the last stage.
    head_loss_fn(head_params, y, labels) -> scalar loss SUM over the
      microbatch (runs on the last stage's outputs).
    head_params: pytree for ``head_loss_fn`` (replicated on every stage).

    Returns ``(loss_sum, dstage_params, dhead_params, dx_micro)`` —
    loss_sum and dhead_params are real on the LAST stage (zeros
    elsewhere); dx_micro (M, micro_b, ...) is real on stage 0 (the embed
    cotangent); dstage_params is each stage's own gradient.  Callers psum
    the first two over ``axis_name`` and feed dx_micro to the embedding's
    vjp.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    m_total = x_micro.shape[0]
    nbuf = 2 * n - 1   # slots live at most 2(n-1) ticks before reuse
    ticks = m_total + 2 * (n - 1)
    fwd_perm = [(i, (i + 1) % n) for i in range(n)]
    bwd_perm = [(i, (i - 1) % n) for i in range(n)]
    is_last = idx == n - 1

    def _cast_varying(a, axes):
        # idempotent pcast: add only the axes the value doesn't carry yet
        missing = tuple(ax for ax in axes
                        if ax not in _compat.vma_of(a))
        return _compat.pcast(a, missing, to="varying") if missing else a

    # activation-shaped carries follow the data: varying over the ring
    # axis AND whatever outer axes the microbatches vary over (e.g. 'data'
    # when composed with data parallelism).  Gradient accumulators are
    # ring-varying only — the vjp's replication transpose data-psums the
    # param cotangents before they reach the accumulator.
    batch_axes = tuple(_compat.vma_of(x_micro)) + (axis_name,)
    varying = lambda a: _cast_varying(a, batch_axes)
    varying_ring = lambda a: _cast_varying(a, (axis_name,))
    zeros_like_v = lambda t: tmap(
        lambda v: varying_ring(jnp.zeros_like(v)), t)
    micro0 = varying(jnp.zeros_like(x_micro[0]))
    # differentiate w.r.t. a ring-VARYING copy of the replicated head
    # params: vjp of an axis-invariant primal inside shard_map triggers
    # the replication transpose (an implicit psum over the axis), which
    # would sum every stage's garbage head-cotangent into the real one
    head_params = tmap(varying_ring, head_params)

    def masked_add(acc, contrib, valid):
        return tmap(lambda a, c: a + jnp.where(valid, c, 0.0), acc, contrib)

    def tick(carry, t):
        (buf_fwd, buf_bwd, slots, dstage, dhead, loss, dx_out) = carry
        m_f = t - idx                      # microbatch in forward here
        m_b = t - 2 * (n - 1) + idx        # microbatch in backward here
        f_valid = (m_f >= 0) & (m_f < m_total)
        b_valid = (m_b >= 0) & (m_b < m_total)

        # ---- forward ----
        feed = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(m_f, 0, m_total - 1), keepdims=False)
        x_in = jnp.where(idx == 0, feed, buf_fwd)
        y = stage_fn(stage_params, x_in)
        slots = jax.lax.dynamic_update_index_in_dim(
            slots, x_in, jnp.mod(t, nbuf), axis=0)

        # ---- head loss + its cotangents (real on the last stage) ----
        lbl = jax.lax.dynamic_index_in_dim(
            labels_micro, jnp.clip(m_f, 0, m_total - 1), keepdims=False)
        loss_m, head_vjp = jax.vjp(
            lambda hp, yy: head_loss_fn(hp, yy, lbl), head_params, y)
        dhp, dy_head = head_vjp(jnp.ones_like(loss_m))
        loss = loss + jnp.where(f_valid & is_last, loss_m, 0.0)
        dhead = masked_add(dhead, dhp, f_valid & is_last)

        # ---- backward (re-linearize the stored input: remat) ----
        # the last stage consumes its own dy from THIS tick (m_b == m_f
        # there); earlier stages consume the cotangent that arrived from
        # the next stage via the reverse ring
        dy_in = jnp.where(is_last, dy_head.astype(jnp.float32),
                          buf_bwd).astype(y.dtype)
        x_saved = jax.lax.dynamic_index_in_dim(
            slots, jnp.mod(t - 2 * (n - 1 - idx), nbuf), keepdims=False)
        _, stage_vjp = jax.vjp(stage_fn, stage_params, x_saved)
        dp, dx = stage_vjp(dy_in)
        dstage = masked_add(dstage, dp, b_valid)
        dx_out = jax.lax.dynamic_update_index_in_dim(
            dx_out,
            jnp.where(b_valid & (idx == 0), dx.astype(jnp.float32), 0.0),
            jnp.clip(m_b, 0, m_total - 1), axis=0)

        # ---- rings: activations forward, cotangents backward ----
        buf_fwd = jax.lax.ppermute(y, axis_name, fwd_perm)
        buf_bwd = jax.lax.ppermute(dx.astype(jnp.float32), axis_name,
                                   bwd_perm)
        return (buf_fwd, buf_bwd, slots, dstage, dhead, loss, dx_out), None

    carry0 = (
        micro0,                                            # buf_fwd
        varying(jnp.zeros(x_micro.shape[1:], jnp.float32)),  # buf_bwd
        varying(jnp.zeros((nbuf,) + x_micro.shape[1:],
                          x_micro.dtype)),                 # slots
        zeros_like_v(stage_params),                        # dstage
        zeros_like_v(head_params),                         # dhead
        varying(jnp.zeros((), jnp.float32)),               # loss
        varying(jnp.zeros(x_micro.shape, jnp.float32)),    # dx_out
    )
    (_, _, _, dstage, dhead, loss, dx_out), _ = jax.lax.scan(
        tick, carry0, jnp.arange(ticks))
    return loss, dstage, dhead, dx_out
