"""Device mesh helpers.

The reference's "cluster" is Spark executors + a driver PS (SURVEY.md §1).
Here the cluster is a ``jax.sharding.Mesh`` over TPU chips: the ``'workers'``
axis replaces Spark partitions, ICI collectives replace the PS socket star.
Multi-host runs initialize via ``jax.distributed`` (see ``initialize()``);
single-host and CPU-simulated runs (``--xla_force_host_platform_device_count``)
use the same code path — the mesh abstracts over both.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

WORKER_AXIS = "workers"


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (replaces Spark job submission + PS bind;
    reference: ``distkeras/trainers.py :: DistributedTrainer.service``).

    No-op on single-process runs; on pods call once per host before building
    a mesh so ``jax.devices()`` is the global device set.
    """
    if coordinator_address is None:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def get_mesh(num_workers: Optional[int] = None,
             axis_name: str = WORKER_AXIS,
             devices: Optional[Sequence] = None) -> Mesh:
    """1-D data-parallel mesh over ``num_workers`` devices.

    ``num_workers`` defaults to every visible device. Using fewer devices than
    available is allowed (benchmark sweeps); more is an error — one worker per
    chip is the TPU-native analogue of one Spark worker per partition.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = num_workers or len(devs)
    if n > len(devs):
        raise ValueError(
            f"num_workers={n} exceeds visible devices ({len(devs)}). "
            "For CPU simulation set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N")
    return Mesh(np.array(devs[:n]), (axis_name,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def worker_sharded(mesh: Mesh, axis: int = 0) -> NamedSharding:
    spec = [None] * (axis + 1)
    spec[axis] = WORKER_AXIS
    return NamedSharding(mesh, P(*spec))


def put_replicated(tree, mesh: Mesh):
    """Place a pytree replicated across the mesh."""
    s = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def put_worker_sharded(tree, mesh: Mesh):
    """Place a pytree whose leaves have a leading 'workers' axis."""
    s = worker_sharded(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)
