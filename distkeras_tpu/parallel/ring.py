"""Ring attention — sequence/context parallelism over a mesh axis.

No reference counterpart (SURVEY.md §2.3: sequence parallelism absent
upstream) — this is the long-context layer of the framework.  The sequence
dim of q/k/v is sharded across devices on a mesh axis; each device keeps its
q shard resident and the k/v shards rotate around the ring with
``lax.ppermute`` (riding ICI neighbor links) while a flash-style *online
softmax* accumulates the attention output:

    num ← num·e^{m−m'} + e^{s−m'}·V_blk      den ← den·e^{m−m'} + Σ e^{s−m'}

so the full (S × S) score matrix never materializes and per-device memory
stays O(S_local²·heads).  After ``ring_size`` rotations every q row has seen
every k/v block; the result equals full attention bit-for-close (f32
accumulation), verified against ``ops.attention.dot_product_attention`` in
``tests/test_attention.py`` (forward and gradients).

Causality is expressed through global positions (block origin × S_local +
row), so late blocks are masked out entirely for early queries — those steps
contribute zeros, keeping the schedule SPMD-uniform (XLA requires identical
programs per device; skipping work data-dependently would desync the ring).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat

SEQ_AXIS = "seq"


def ring_attention(q, k, v, axis_name: str = SEQ_AXIS,
                   causal: bool = False, scale: Optional[float] = None,
                   block_k: Optional[int] = None,
                   window: Optional[int] = None):
    """Collective attention over sequence shards — call *inside* shard_map.

    q: local shard (B, S_local, H, Dh); k, v: (B, S_local, Hkv, Dh) with
    Hkv | H (grouped-query attention — k/v rotate the ring at Hkv heads,
    so GQA shrinks the ppermute payload by H/Hkv too).  Sequence-sharded
    on ``axis_name``; returns the local (B, S_local, H, Dh) output in
    q.dtype.

    ``block_k``: chunk each rotation's local attend over k sub-blocks of
    this size (blockwise attention), bounding the score tensor at
    (B, H, S_local, block_k) instead of (B, H, S_local, S_local) — the
    long-context memory knob when local shards are themselves large.  The
    math is identical (same online-softmax recurrence, finer grain).

    ``window`` (requires ``causal``): sliding-window masking on global
    positions — query p sees keys in (p - window, p], consistent with
    ``ops.attention.dot_product_attention(window=...)``.  Rotations whose
    block is entirely out of window still run (SPMD-uniform schedule) but
    contribute zeros.
    """
    from ..ops.attention import validate_window
    window = validate_window(window, causal)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"num_heads {h} not divisible by kv heads {hkv}")
    g = h // hkv
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    if block_k is not None and s_loc % block_k:
        raise ValueError(f"S_local {s_loc} % block_k {block_k} != 0")

    # grouped-query layout: accumulators carry (B, Hkv, G, Sq, ...) and
    # collapse back to H = Hkv*G heads at the end; G == 1 is classic MHA
    q32 = (q.astype(jnp.float32) * scale).reshape(b, s_loc, hkv, g, d)
    q_pos = idx * s_loc + jnp.arange(s_loc)
    # send-to-left rotation: after r steps the resident block originated at
    # ring position (idx + r) mod n
    perm = [(i, (i - 1) % n) for i in range(n)]

    def attend_chunk(acc, k_blk, v_blk, k0):
        """One online-softmax update; ``k0`` = global position of
        k_blk[:, 0]."""
        num, den, mx = acc
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", q32,
                            k_blk.astype(jnp.float32))
        if causal:
            k_pos = k0 + jnp.arange(k_blk.shape[1])
            hide = k_pos[None, :] > q_pos[:, None]
            if window is not None:
                hide = hide | (k_pos[None, :] <= q_pos[:, None] - window)
            scores = jnp.where(hide[None, None, None], -jnp.inf, scores)
        blk_max = jnp.max(scores, axis=-1)                     # (B,Hkv,G,Sq)
        new_mx = jnp.maximum(mx, blk_max)
        # fully-masked-so-far rows keep mx = -inf; shift by 0 there so the
        # exps below stay NaN-free (e^{-inf-0} = 0)
        safe = jnp.where(jnp.isneginf(new_mx), 0.0, new_mx)
        p = jnp.exp(scores - safe[..., None])               # (B,Hkv,G,Sq,Bk)
        corr = jnp.exp(mx - safe)                           # (B,Hkv,G,Sq)
        num = num * corr[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_blk.astype(jnp.float32))
        den = den * corr + jnp.sum(p, axis=-1)
        return num, den, new_mx

    def attend(acc, k_blk, v_blk, src):
        if block_k is None:
            return attend_chunk(acc, k_blk, v_blk, src * s_loc)

        def chunk(acc, c):
            kb = jax.lax.dynamic_slice_in_dim(k_blk, c * block_k, block_k,
                                              axis=1)
            vb = jax.lax.dynamic_slice_in_dim(v_blk, c * block_k, block_k,
                                              axis=1)
            return attend_chunk(acc, kb, vb, src * s_loc + c * block_k), None

        acc, _ = jax.lax.scan(chunk, acc, jnp.arange(s_loc // block_k))
        return acc

    def body(carry, r):
        # double-buffered schedule: issue the NEXT block's ppermute before
        # attending the resident block — the transfer and the attend are
        # independent, so XLA's async collective-permute (start/done pair)
        # overlaps the ICI hop with the compute instead of serializing
        # rotate→attend (round-3 VERDICT weak #5).  Attend order is
        # unchanged (blocks idx, idx+1, … mod n), so results stay
        # bit-identical to the serial schedule.
        k_blk, v_blk, num, den, mx = carry
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        num, den, mx = attend((num, den, mx), k_blk, v_blk,
                              jnp.mod(idx + r, n))
        return (k_nxt, v_nxt, num, den, mx), None

    # accumulators start as constants (device-invariant); mark them varying
    # over the ring axis so the scan carry types stay fixed once the online
    # update makes them data-dependent (attending the own block below also
    # picks up whatever outer shard_map axes q/k/v vary over)
    varying = lambda a: _compat.pcast(a, axis_name, to="varying")
    acc0 = (varying(jnp.zeros((b, hkv, g, s_loc, d), jnp.float32)),
            varying(jnp.zeros((b, hkv, g, s_loc), jnp.float32)),
            varying(jnp.full((b, hkv, g, s_loc), -jnp.inf, jnp.float32)))
    num, den, mx = attend(acc0, k, v, idx)                      # own block
    if n > 1:
        # prefetch block idx+1 — independent of the own-block attend above,
        # so the transfer overlaps it too
        k_blk = jax.lax.ppermute(k, axis_name, perm)
        v_blk = jax.lax.ppermute(v, axis_name, perm)
        (k_last, v_last, num, den, mx), _ = jax.lax.scan(
            body, (k_blk, v_blk, num, den, mx), jnp.arange(1, n - 1))
        # the last resident block needs no further rotation: attend it
        # outside the loop, keeping the ring at exactly n-1 permutes
        num, den, _ = attend((num, den, mx), k_last, v_last,
                             jnp.mod(idx + n - 1, n))
    den = jnp.where(den == 0.0, 1.0, den)
    out = num / den[..., None]                               # (B,Hkv,G,Sq,Dh)
    out = jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(b, s_loc, h, d)
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = SEQ_AXIS,
                        causal: bool = False,
                        scale: Optional[float] = None,
                        block_k: Optional[int] = None,
                        window: Optional[int] = None):
    """Convenience wrapper: global (B, S, H, Dh) arrays in, sequence sharded
    over ``mesh[axis_name]``, ring attention, global array out.  For models
    already running under shard_map, call ``ring_attention`` directly."""
    spec = P(None, axis_name, None, None)
    fn = _compat.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, axis_name, causal, scale,
                                        block_k, window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    sharding = NamedSharding(mesh, spec)
    return fn(jax.device_put(q, sharding), jax.device_put(k, sharding),
              jax.device_put(v, sharding))
