"""Shared train-step machinery for the model-parallel transformers.

Both ``ParallelTransformerLM`` (dp × sp × tp + ep) and
``PipelineTransformerLM`` (dp × pp) compile the same shape of program: a
``shard_map``'d value_and_grad + optax update over mesh-sharded params, with
the optimizer state sharded like the params it tracks.  This module holds
that machinery once, in a model-agnostic place.
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

tmap = jax.tree_util.tree_map


def opt_partition_specs(optimizer, params, param_specs):
    """PartitionSpecs for an optax state over sharded params.

    Optax moment trees (mu/nu/trace...) embed the full param tree, so every
    state leaf's key path *ends with* some param's key path — match on that
    suffix to inherit the param's spec; leaves with no param suffix (step
    counters, scalars) replicate."""
    opt_shape = jax.eval_shape(optimizer.init, params)
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    path_to_spec = {
        tuple(str(k) for k in path): sp
        for (path, _), sp in zip(
            jax.tree_util.tree_flatten_with_path(params)[0], spec_leaves)}

    def leaf_spec(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):
            sp = path_to_spec.get(keys[start:])
            if sp is not None:
                return sp
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, opt_shape)


def build_train_step(mesh: Mesh, local_loss, param_specs, batch_spec,
                     optimizer: optax.GradientTransformation, params,
                     loss_and_grads=None):
    """(opt_state, jitted step): step(params, opt, tokens, labels) ->
    (params, opt, loss).

    ``local_loss(params, tokens, labels)`` runs *inside* shard_map over
    ``mesh`` — it sees local shards and is responsible for its own
    collectives.  State buffers are donated.  Pass ``loss_and_grads`` to
    supply gradients another way than reverse-mode over ``local_loss``
    (e.g. the hand-scheduled 1F1B pipeline backward); it has the
    ``value_and_grad`` signature and also runs inside shard_map.
    """
    opt_sp = opt_partition_specs(optimizer, params, param_specs)
    if loss_and_grads is None:
        loss_and_grads = jax.value_and_grad(local_loss)

    def local_step(params, opt_state, tokens, labels):
        loss, grads = loss_and_grads(params, tokens, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    opt_state = jax.jit(
        optimizer.init,
        out_shardings=tmap(lambda s: NamedSharding(mesh, s), opt_sp,
                           is_leaf=lambda x: isinstance(x, P)))(params)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(param_specs, opt_sp, batch_spec, batch_spec),
        out_specs=(param_specs, opt_sp, P())),
        donate_argnums=(0, 1))
    return opt_state, step
