"""Shared train-step machinery for the model-parallel transformers.

Both ``ParallelTransformerLM`` (dp × sp × tp + ep) and
``PipelineTransformerLM`` (dp × pp) compile the same shape of program: a
``shard_map``'d value_and_grad + optax update over mesh-sharded params, with
the optimizer state sharded like the params it tracks.  This module holds
that machinery once, in a model-agnostic place.

``zero_axis`` adds ZeRO-1 optimizer-state sharding: optax moment leaves are
additionally partitioned over the data axis (each data shard owns 1/dp of
every mu/nu/trace buffer), expressed purely through sharding annotations —
the update stays ordinary optax, and XLA GSPMD inserts the slice of the
(replicated) gradients, the local moment update, and the all-gather of the
applied param updates.  This is the "annotate shardings, let the compiler
place collectives" recipe, not a hand-rolled reduce-scatter schedule.
"""

from __future__ import annotations

from typing import Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat

tmap = jax.tree_util.tree_map


def opt_partition_specs(optimizer, params, param_specs):
    """PartitionSpecs for an optax state over sharded params.

    Optax moment trees (mu/nu/trace...) embed the full param tree, so every
    state leaf's key path *ends with* some param's key path — match on that
    suffix to inherit the param's spec; leaves with no param suffix (step
    counters, scalars) replicate.  Returns (specs, state shape tree) so
    callers needing the shapes (zero_shard_specs) don't re-trace init."""
    opt_shape = jax.eval_shape(optimizer.init, params)
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    path_to_spec = {
        tuple(str(k) for k in path): sp
        for (path, _), sp in zip(
            jax.tree_util.tree_flatten_with_path(params)[0], spec_leaves)}

    def leaf_spec(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(len(keys)):
            sp = path_to_spec.get(keys[start:])
            if sp is not None:
                return sp
        return P()

    return jax.tree_util.tree_map_with_path(leaf_spec, opt_shape), opt_shape


def shard_specs_over_axis(specs, shapes, mesh: Mesh, axis: str):
    """Partition each leaf's spec over ``axis`` where a dimension allows it.

    For every leaf, the first dimension that is (a) unsharded in the
    inherited spec and (b) divisible by the axis size takes ``axis``;
    leaves with no such dimension (scalars, odd shapes) stay as inherited —
    per-leaf fallback, never an error, so any model shape benefits where it
    can.  ``shapes`` is any tree of objects with ``.shape`` (concrete arrays
    or ShapeDtypeStructs) mirroring ``specs``."""
    n_shards = mesh.shape[axis]

    def shard_leaf(spec, shape):
        if not isinstance(spec, P):
            return spec
        entries = list(spec) + [None] * (len(shape.shape) - len(spec))
        if any(axis == e or (isinstance(e, tuple) and axis in e)
               for e in entries):
            return spec  # already partitioned over this axis
        for i, (e, n) in enumerate(zip(entries, shape.shape)):
            if e is None and n % n_shards == 0 and n > 0:
                entries[i] = axis
                return P(*entries)
        return spec

    return tmap(shard_leaf, specs, shapes,
                is_leaf=lambda x: isinstance(x, P))


def zero_shard_specs(opt_specs, opt_shapes, mesh: Mesh, zero_axis: str):
    """ZeRO-1: partition each optimizer-state leaf's spec over ``zero_axis``
    (see ``shard_specs_over_axis`` for the per-leaf rule)."""
    return shard_specs_over_axis(opt_specs, opt_shapes, mesh, zero_axis)


def _constrain(mesh: Mesh, tree, specs):
    """Annotate every array leaf of ``tree`` with its spec's NamedSharding.

    flatten_up_to semantics: ``tree``'s array leaves pair with whole P
    entries in ``specs`` (P is a tuple subclass, so a direct flatten of
    specs would recurse into it)."""
    return tmap(lambda x, s: jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, s)), tree, specs)


def build_train_step(mesh: Mesh, local_loss, param_specs, batch_spec,
                     optimizer: optax.GradientTransformation, params,
                     loss_and_grads=None, zero_axis: Optional[str] = None,
                     fsdp_axis: Optional[str] = None):
    """(opt_state, jitted step): step(params, opt, tokens, labels) ->
    (params, opt, loss).

    ``local_loss(params, tokens, labels)`` runs *inside* shard_map over
    ``mesh`` — it sees local shards and is responsible for its own
    collectives.  State buffers are donated.  Pass ``loss_and_grads`` to
    supply gradients another way than reverse-mode over ``local_loss``
    (e.g. the hand-scheduled 1F1B pipeline backward); it has the
    ``value_and_grad`` signature and also runs inside shard_map.

    ``zero_axis``: a mesh axis name (usually the data axis) to ZeRO-1-shard
    the optimizer state over.  The grad computation is unchanged (grads
    come out of shard_map replicated over the data axis, courtesy of the
    psum transpose); the optax update then runs under plain jit with the
    moment buffers annotated ``zero_axis``-sharded, so GSPMD compiles the
    per-shard moment update + param-update all-gather.  Losses match the
    unsharded path to float tolerance (asserted at rtol 1e-6 — the update
    math is identical, only GSPMD's fusion/reduction order differs from
    the shard_map program's); HBM for mu/nu drops by the axis size.

    ``fsdp_axis``: ZeRO-3 / fully-sharded data parallelism — the *params
    themselves* (not just the moments) are additionally partitioned over
    the axis at rest, again purely through sharding annotations: the step
    constrains params to their FSDP specs on entry and exit, the grad
    shard_map still sees logically-full params (GSPMD compiles the
    all-gather in, and fuses the grad psum + FSDP slice into a
    reduce-scatter where profitable), and the optax update runs on the
    owned 1/n slice with moments inheriting the FSDP layout.  Param,
    grad-at-rest, and moment HBM all drop by the axis size; supersedes
    ``zero_axis``.  The first call accepts params in any layout (outputs
    come back FSDP-sharded, so the steady state is sharded end-to-end).
    """
    if loss_and_grads is None:
        loss_and_grads = jax.value_and_grad(local_loss)

    if fsdp_axis is not None:
        if fsdp_axis not in mesh.shape:
            raise ValueError(f"fsdp_axis {fsdp_axis!r} not in mesh axes "
                             f"{tuple(mesh.shape)}")
        store_specs = shard_specs_over_axis(param_specs, params, mesh,
                                            fsdp_axis)
        # moments inherit the FSDP param layout (key-path suffix match);
        # a second pass catches leaves whose param had no divisible dim
        # but whose moment does (none in practice — belt and braces)
        opt_sp, opt_shapes = opt_partition_specs(optimizer, params,
                                                 store_specs)
        opt_sp = shard_specs_over_axis(opt_sp, opt_shapes, mesh, fsdp_axis)
        ns = lambda tree: tmap(lambda s: NamedSharding(mesh, s), tree,
                               is_leaf=lambda x: isinstance(x, P))
        opt_state = jax.jit(optimizer.init, out_shardings=ns(opt_sp))(params)

        grads_fn = _compat.shard_map(
            loss_and_grads, mesh=mesh,
            in_specs=(param_specs, batch_spec, batch_spec),
            out_specs=(P(), param_specs))

        def fsdp_step(params, opt_state, tokens, labels):
            # at-rest layout: each fsdp shard owns 1/n of every param leaf;
            # the shard_map boundary below is where GSPMD gathers them
            params = _constrain(mesh, params, store_specs)
            loss, grads = grads_fn(params, tokens, labels)
            # grads leave the shard_map replicated over the data axis; the
            # constraint lets GSPMD lower psum + slice to a reduce-scatter
            grads = _constrain(mesh, grads, store_specs)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            opt_state = _constrain(mesh, opt_state, opt_sp)
            params = _constrain(mesh, optax.apply_updates(params, updates),
                                store_specs)
            return params, opt_state, loss

        return opt_state, jax.jit(fsdp_step, donate_argnums=(0, 1))

    opt_sp, opt_shapes = opt_partition_specs(optimizer, params, param_specs)
    if zero_axis is not None:
        if zero_axis not in mesh.shape:
            raise ValueError(f"zero_axis {zero_axis!r} not in mesh axes "
                             f"{tuple(mesh.shape)}")
        opt_sp = zero_shard_specs(opt_sp, opt_shapes, mesh, zero_axis)

    # opt_sp is final here (zero resharding included), so both step flavors
    # share one sharded init
    opt_state = jax.jit(
        optimizer.init,
        out_shardings=tmap(lambda s: NamedSharding(mesh, s), opt_sp,
                           is_leaf=lambda x: isinstance(x, P)))(params)

    if zero_axis is not None:
        grads_fn = _compat.shard_map(
            loss_and_grads, mesh=mesh,
            in_specs=(param_specs, batch_spec, batch_spec),
            out_specs=(P(), param_specs))
        def zero_step(params, opt_state, tokens, labels):
            loss, grads = grads_fn(params, tokens, labels)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            # the annotations below are where ZeRO lives: moments stay
            # zero_axis-sharded (each data shard updates only its slice of
            # the elementwise optax math), params return replicated (GSPMD
            # all-gathers the applied updates once per step)
            opt_state = _constrain(mesh, opt_state, opt_sp)
            params = _constrain(mesh, optax.apply_updates(params, updates),
                                param_specs)
            return params, opt_state, loss

        return opt_state, jax.jit(zero_step, donate_argnums=(0, 1))

    def local_step(params, opt_state, tokens, labels):
        loss, grads = loss_and_grads(params, tokens, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    step = jax.jit(_compat.shard_map(
        local_step, mesh=mesh,
        in_specs=(param_specs, opt_sp, batch_spec, batch_spec),
        out_specs=(param_specs, opt_sp, P())),
        donate_argnums=(0, 1))
    return opt_state, step
