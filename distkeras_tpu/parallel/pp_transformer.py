"""PipelineTransformerLM — a trainable dp × pp transformer.

Round-2 VERDICT weak #7: ``pipeline_apply`` was a primitive demonstrated on a
toy stage function; nobody could train a real model with pipeline
parallelism.  This module is the integrated form (no reference counterpart —
SURVEY.md §2.3: pipeline parallelism absent upstream): a decoder-only causal
LM over a ``('data', 'stage')`` mesh whose single jitted train step

 - shards the batch over 'data' (data parallelism),
 - splits the layer stack into ``mesh.shape['stage']`` pipeline stages, one
   stage's layer params per device (sharded ``P('stage')``), and streams
   GPipe microbatches through ``pipeline_apply``'s ppermute ring, forward
   AND backward (reverse-mode autodiff through the scan + ppermute is the
   pipelined backward);
 - keeps embed/pos/ln_f/head replicated: every stage computes the cheap
   embedding and head so the SPMD program stays uniform; their gradients are
   psummed by shard_map's replication transpose automatically.

The stage function is ``layers_per_stage`` pre-LN transformer blocks run by
a ``lax.scan`` over the stage's stacked layer params — shape-preserving
(B_micro, S, D) → same, exactly what the pipeline schedule requires.

``reference_forward`` computes the identical math on one device; tests
assert loss/grad equality between the pipelined and sequential forms, and
``__graft_entry__.dryrun_multichip`` compiles this train step as its
pipeline-parallel stage.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import dot_product_attention
from .pipeline import pipeline_1f1b, pipeline_apply

tmap = jax.tree_util.tree_map


class PipelineTransformerLM:
    """Causal LM over a ('data', 'stage') mesh with GPipe microbatching."""

    def __init__(self, vocab_size: int, seq_len: int, d_model: int,
                 num_heads: int, num_layers: int, mlp_dim: int, mesh: Mesh,
                 *, num_microbatches: int = 2, compute_dtype=jnp.bfloat16,
                 remat: bool = False, schedule: str = "gpipe",
                 data_axis: str = "data", stage_axis: str = "stage",
                 model_axis: Optional[str] = None):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.mlp_dim = mlp_dim
        self.mesh = mesh
        self.num_microbatches = int(num_microbatches)
        self.compute_dtype = compute_dtype
        # remat the per-tick stage compute: the GPipe backward otherwise
        # stores every block's internals for all M+n-1 ticks; with remat
        # only the tick-boundary activations persist (the standard
        # activation-memory/FLOPs trade at real depth)
        self.remat = bool(remat)
        # 'gpipe': autodiff through the forward pipeline (backward after
        # all forwards — activation state O(M)).  '1f1b': hand-built
        # one-forward-one-backward schedule (pipeline.pipeline_1f1b) —
        # cotangents chase activations through a second ring, per-stage
        # activation buffer O(n) independent of M
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"schedule must be 'gpipe' or '1f1b', "
                             f"got {schedule!r}")
        self.schedule = schedule
        self.data_axis = data_axis
        self.stage_axis = stage_axis
        # model_axis: Megatron tensor parallelism INSIDE each pipeline
        # stage (3-D dp × pp × tp): qkv/w1 column-split and wo/w2
        # row-split over this mesh axis, one psum per attention/MLP —
        # activations stay replicated in value over 'model', so the
        # pipeline rings are unchanged
        self.model_axis = model_axis
        self.tp = mesh.shape[model_axis] if model_axis is not None else 1
        self.n_stages = mesh.shape[stage_axis]
        self.dp = mesh.shape[data_axis]
        if num_layers % self.n_stages:
            raise ValueError(
                f"num_layers {num_layers} % stages {self.n_stages} != 0")
        self.layers_per_stage = num_layers // self.n_stages
        if d_model % num_heads:
            raise ValueError(f"d_model {d_model} % heads {num_heads} != 0")
        if num_heads % self.tp:
            raise ValueError(f"num_heads {num_heads} % tp {self.tp} != 0")
        if mlp_dim % self.tp:
            raise ValueError(f"mlp_dim {mlp_dim} % tp {self.tp} != 0")
        self.head_dim = d_model // num_heads

    # -- params ---------------------------------------------------------------
    def _layer_leaf_shapes(self):
        d, f = self.d_model, self.mlp_dim
        return {
            "ln1": (d,), "ln2": (d,),
            "wq": (d, d), "wk": (d, d), "wv": (d, d), "wo": (d, d),
            "w1": (d, f), "b1": (f,), "w2": (f, d), "b2": (d,),
        }

    def param_specs(self):
        st, md = self.stage_axis, self.model_axis
        if md is None:
            layer_specs = {k: P(st) for k in self._layer_leaf_shapes()}
        else:
            # Megatron split kind per leaf, applied on top of the stage
            # stacking (n, lps, ...): "col" = trailing dim (qkv/w1 and
            # b1, which follows w1's columns), "row" = input dim (wo/w2);
            # leaves without an entry stay replicated (ln/b2 — correct,
            # just unsplit, for any future leaf too)
            split = {"wq": "col", "wk": "col", "wv": "col", "w1": "col",
                     "b1": "col", "wo": "row", "w2": "row"}
            to_spec = {
                ("col", 2): P(st, None, None, md),   # (n,lps,in,out)
                ("col", 1): P(st, None, md),         # (n,lps,out)
                ("row", 2): P(st, None, md, None),
            }
            layer_specs = {
                k: to_spec.get((split.get(k), len(shape)), P(st))
                for k, shape in self._layer_leaf_shapes().items()}
        return {"embed": P(), "pos": P(), "ln_f": P(), "head": P(),
                "layers": layer_specs}

    def init(self, rng) -> Any:
        """Params with per-layer leaves stacked
        (n_stages, layers_per_stage, ...) and sharded P('stage')."""
        d = self.d_model
        n, lps = self.n_stages, self.layers_per_stage
        keys = iter(jax.random.split(rng, 4 + 10 * self.num_layers))

        def w(shape):
            return (jax.random.normal(next(keys), shape, jnp.float32)
                    / math.sqrt(max(shape[-2], 1)))

        def stack(fn):
            rows = [[fn() for _ in range(lps)] for _ in range(n)]
            return jnp.stack([jnp.stack(r) for r in rows])

        layers = {}
        for name, shape in self._layer_leaf_shapes().items():
            if name.startswith("ln"):
                layers[name] = jnp.ones((n, lps) + shape, jnp.float32)
            elif name.startswith("b"):
                layers[name] = jnp.zeros((n, lps) + shape, jnp.float32)
            else:
                layers[name] = stack(lambda shape=shape: w(shape))
        params = {
            "embed": 0.02 * jax.random.normal(
                next(keys), (self.vocab_size, d), jnp.float32),
            "pos": 0.02 * jax.random.normal(
                next(keys), (self.seq_len, d), jnp.float32),
            "ln_f": jnp.ones((d,), jnp.float32),
            "head": w((d, self.vocab_size)),
            "layers": layers,
        }
        specs = self.param_specs()
        return tmap(
            lambda a, sp: jax.device_put(a, NamedSharding(self.mesh, sp)),
            params, specs)

    # -- the per-layer block (shared by pipeline + reference) -----------------
    def _ln(self, scale, h):
        h32 = h.astype(jnp.float32)
        mu = jnp.mean(h32, axis=-1, keepdims=True)
        var = jnp.var(h32, axis=-1, keepdims=True)
        return ((h32 - mu) * jax.lax.rsqrt(var + 1e-5)
                * scale).astype(self.compute_dtype)

    def _block(self, lp, x):
        """One pre-LN transformer block on (B, S, D)."""
        cdt = self.compute_dtype
        b, s, d = x.shape
        h = self._ln(lp["ln1"], x)

        def proj(wname):
            y = jax.lax.dot_general(
                h, lp[wname].astype(cdt), (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(cdt)
            return y.reshape(b, s, self.num_heads, self.head_dim)

        attn = dot_product_attention(proj("wq"), proj("wk"), proj("wv"),
                                     causal=True)
        attn = attn.reshape(b, s, d)
        attn = jax.lax.dot_general(
            attn.astype(cdt), lp["wo"].astype(cdt), (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        x = x + attn.astype(cdt)

        h = self._ln(lp["ln2"], x)
        y = jax.lax.dot_general(
            h, lp["w1"].astype(cdt), (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + lp["b1"]
        y = jax.nn.gelu(y).astype(cdt)
        y = jax.lax.dot_general(
            y, lp["w2"].astype(cdt), (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + lp["b2"]
        return x + y.astype(cdt)

    def _block_tp(self, lp, x):
        """The same block with Megatron tensor parallelism over
        ``model_axis`` (call inside shard_map only: one psum per
        attention/MLP).  lp leaves are this shard's local slices."""
        from .tp import tp_mlp, tp_self_attention
        cdt = self.compute_dtype
        h = self._ln(lp["ln1"], x)
        attn = tp_self_attention(
            h, lp["wq"], lp["wk"], lp["wv"], lp["wo"],
            num_local_heads=self.num_heads // self.tp,
            head_dim=self.head_dim, axis_name=self.model_axis,
            causal=True, compute_dtype=cdt)
        x = x + attn.astype(cdt)
        h = self._ln(lp["ln2"], x)
        y = tp_mlp(h, lp["w1"], lp["b1"], lp["w2"], lp["b2"],
                   axis_name=self.model_axis, compute_dtype=cdt)
        return x + y.astype(cdt)

    def _stage_fn(self, stage_layers, x, tp: bool = False):
        """Run this stage's ``layers_per_stage`` blocks (scan over the
        stacked layer params) — shape-preserving, as the pipeline needs.
        ``tp=True`` selects the tensor-parallel block (sharded weights,
        inside shard_map); the dense block doubles as the no-mesh oracle
        on full-width params."""
        block = self._block_tp if tp else self._block

        def body(h, lp):
            return block(lp, h), None

        out, _ = jax.lax.scan(body, x, stage_layers)
        return out

    # -- forward/loss ---------------------------------------------------------
    def _embed(self, params, tokens):
        cdt = self.compute_dtype
        x = params["embed"].astype(cdt)[tokens]
        return x + params["pos"].astype(cdt)[None, :tokens.shape[1]]

    def _head_loss(self, params, x, labels):
        cdt = self.compute_dtype
        x = self._ln(params["ln_f"], x)
        logits = jax.lax.dot_general(
            x.astype(cdt), params["head"].astype(cdt),
            (((2,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return -jnp.sum(picked), jnp.asarray(picked.size, jnp.float32)

    def _microbatch_prologue(self, params, tokens):
        """Shared by the gpipe and 1f1b paths: the batch-divisibility
        check, this device's stage-slice squeeze of the stacked layer
        params ((1, lps, ...) → (lps, ...)), and the (optionally remat'd)
        shape-preserving stage program."""
        m = self.num_microbatches
        b_loc = tokens.shape[0]
        if b_loc % m:
            raise ValueError(
                f"local batch {b_loc} % microbatches {m} != 0")
        stage_layers = tmap(lambda v: v[0], params["layers"])
        stage = lambda sp, h: self._stage_fn(sp,
                                             h.astype(self.compute_dtype),
                                             tp=self.tp > 1)
        if self.remat:
            stage = jax.checkpoint(stage)
        return m, b_loc, stage_layers, stage

    def _local_loss(self, params, tokens, labels):
        """Inside shard_map over ('data', 'stage')."""
        m, b_loc, stage_layers, stage = self._microbatch_prologue(params,
                                                                  tokens)
        x = self._embed(params, tokens)                  # (B_loc, S, D)
        micro = x.reshape((m, b_loc // m) + x.shape[1:])
        out = pipeline_apply(stage, stage_layers, micro,
                             axis_name=self.stage_axis)
        # outputs are real only on the last stage (zeros elsewhere): every
        # stage runs the head on its own buffer (SPMD-uniform — garbage on
        # non-last stages) and the last stage's SCALARS are selected by
        # mask + psum.  This replaces the previous full-activation psum
        # broadcast, which shipped M·B·S·D floats to every stage just to
        # compute a number only one stage could produce (round-3 VERDICT
        # weak #4); the only cross-stage payload now is two scalars.
        # Backward stays correct for free: non-last stages' masked scalars
        # get zero cotangent, so no garbage gradient flows anywhere.
        x = out.reshape((b_loc,) + x.shape[1:]).astype(self.compute_dtype)
        local_sum, _ = self._head_loss(params, x, labels)
        n = jax.lax.psum(1, self.stage_axis)
        is_last = (jax.lax.axis_index(self.stage_axis) == n - 1)
        total = jax.lax.psum(
            jnp.where(is_last, local_sum, jnp.zeros((), jnp.float32)),
            (self.data_axis, self.stage_axis))
        # the token count is static (b_loc·S per data shard, one real copy
        # across stages) — no collective needed
        count = float(self.dp * b_loc * tokens.shape[1])
        return total / count

    def _local_loss_and_grads_1f1b(self, params, tokens, labels):
        """Manual loss + gradients via the 1F1B schedule (no outer
        ``jax.grad`` — ``pipeline_1f1b`` builds the backward from
        per-stage vjps).  Inside shard_map over ('data', 'stage').

        The implicit-psum bookkeeping: stage-layer and head cotangents are
        data-psummed automatically by the vjp's replication transpose (the
        primals are data-invariant).  Explicit collectives: the scalar
        loss reduction, the head-grad stage broadcast, and one stage-axis
        psum of the (B_loc, S, D) embedding cotangent (real on stage 0,
        zeros elsewhere — the embed pullback demands a cotangent with the
        embed output's exact varying axes).
        """
        m, b_loc, stage_layers, stage = self._microbatch_prologue(params,
                                                                  tokens)
        s_len = tokens.shape[1]
        embed_sub = {"embed": params["embed"], "pos": params["pos"]}
        head_sub = {"ln_f": params["ln_f"], "head": params["head"]}

        x, embed_pull = jax.vjp(lambda ep: self._embed(ep, tokens),
                                embed_sub)
        micro = x.reshape((m, b_loc // m) + x.shape[1:])
        labels_micro = labels.reshape(m, b_loc // m, s_len)

        loss_sum, dstage, dhead, dx_micro = pipeline_1f1b(
            stage, stage_layers, micro, labels_micro,
            lambda hp, y, lbl: self._head_loss(hp, y, lbl)[0],
            head_sub, axis_name=self.stage_axis)

        # loss: real on the last stage only, per data shard → global mean
        count = float(self.dp * b_loc * s_len)
        loss = jax.lax.psum(loss_sum,
                            (self.data_axis, self.stage_axis)) / count
        # embed/pos: collapse the stage axis first (real on stage 0, zeros
        # elsewhere — the pullback demands the cotangent carry x's exact
        # varying axes); the pullback then data-psums internally
        dx_full = dx_micro.reshape((b_loc,) + x.shape[1:])
        dx_full = jax.lax.psum(dx_full, self.stage_axis).astype(x.dtype)
        (dembed,) = embed_pull(dx_full)
        # head/ln_f: real on the last stage, zeros elsewhere → broadcast
        dhead = tmap(lambda g: jax.lax.psum(g, self.stage_axis), dhead)
        grads = {
            "embed": dembed["embed"], "pos": dembed["pos"],
            "ln_f": dhead["ln_f"], "head": dhead["head"],
            # restore the (1, lps, ...) leading stage axis of the params
            "layers": tmap(lambda g: g[None], dstage),
        }
        # manual grads are for the loss SUM; match the mean-loss scaling
        grads = tmap(lambda g: g / count, grads)
        return loss, grads

    def reference_forward_loss(self, params, tokens, labels):
        """The same math with no mesh: stages applied sequentially on one
        device — the correctness oracle for the pipelined step."""
        x = self._embed(params, tokens)
        layers = params["layers"]
        for st in range(self.n_stages):
            stage_layers = tmap(lambda v: v[st], layers)
            x = self._stage_fn(stage_layers, x)
        local_sum, local_cnt = self._head_loss(
            params, x.astype(self.compute_dtype), labels)
        return local_sum / local_cnt

    # -- train step -----------------------------------------------------------
    def compile_train_step(self, optimizer: optax.GradientTransformation,
                           params, zero: bool = False, fsdp: bool = False):
        """(opt_state, jitted step): step(params, opt, tokens, labels) ->
        (params, opt, loss); tokens/labels (B, S) int32 sharded P('data').
        ``schedule='1f1b'`` swaps the autodiff GPipe backward for the
        hand-scheduled one-forward-one-backward program (same loss/grads,
        O(n) activation state).  ``zero=True`` ZeRO-1-shards the optimizer
        state over the data axis; ``fsdp=True`` ZeRO-3-shards params AND
        moments there (see ``train_step.build_train_step``)."""
        from .train_step import build_train_step
        return build_train_step(
            self.mesh, self._local_loss, self.param_specs(),
            P(self.data_axis), optimizer, params,
            loss_and_grads=(self._local_loss_and_grads_1f1b
                            if self.schedule == "1f1b" else None),
            zero_axis=self.data_axis if zero else None,
            fsdp_axis=self.data_axis if fsdp else None)

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.data_axis))

    def bubble_fraction(self) -> float:
        """Analytic fill/drain bubble of this instance's schedule.

        GPipe: forward scan of ``M + n - 1`` ticks plus its autodiff
        mirror — ``2(n-1)`` of ``2(M + n - 1)`` tick-halves are garbage,
        i.e. ``(n-1)/(M+n-1)``.  1F1B: one combined fwd+bwd scan of
        ``M + 2(n-1)`` ticks with ``M`` real forwards (and ``M`` real
        backwards) each — bubble ``2(n-1)/(M+2(n-1))``, slightly larger at
        equal M but with the O(n) activation buffer.  Shrinks with more
        microbatches; ``examples/pp_bubble_bench.py`` measures how closely
        wall-clock follows it."""
        m, n = self.num_microbatches, self.n_stages
        if self.schedule == "1f1b":
            return 2 * (n - 1) / (m + 2 * (n - 1))
        return (n - 1) / (m + n - 1)
