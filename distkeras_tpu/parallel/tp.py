"""Tensor-parallel building blocks (Megatron-style) for shard_map programs.

No reference counterpart (SURVEY.md §2.3: tensor parallelism absent upstream
— its models fit on one 2016 CPU).  On TPU, tensor parallelism is how a model
larger than one chip's HBM trains: weight matrices are split across a mesh
axis and the *activations* are exchanged over ICI instead.

The two primitives compose into the standard one-collective-per-block
pattern:

  column_parallel:  y_local = x @ W[:, shard]          (no communication)
  row_parallel:     y = psum_tp(x_local @ W[shard, :]) (one psum)

so an MLP (column → gelu → row) and an attention block (qkv column-split by
head, output row-split) each cost exactly one ``psum`` over the 'model' axis
— the Megatron schedule.  All functions here assume they run inside
``shard_map`` with ``axis_name`` a live mesh axis; weights arrive already
sharded (leading ``W.shape[...]`` are the *local* shard sizes).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

MODEL_AXIS = "model"


def column_parallel_dense(x, kernel, bias=None, *,
                          compute_dtype=jnp.bfloat16):
    """x @ W_col_shard. Kernel is the local (D, F/tp) shard; output stays
    sharded on its trailing dim — zero communication."""
    y = jax.lax.dot_general(
        x.astype(compute_dtype), kernel.astype(compute_dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias
    return y


def row_parallel_dense(x, kernel, bias=None, *, axis_name: str = MODEL_AXIS,
                       compute_dtype=jnp.bfloat16):
    """psum(x_shard @ W_row_shard). Kernel is the local (F/tp, D) shard; the
    partial products reduce over ICI — the block's single collective.  Bias
    is added once, after the reduce (it is replicated)."""
    y = jax.lax.dot_general(
        x.astype(compute_dtype), kernel.astype(compute_dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    y = jax.lax.psum(y, axis_name)
    if bias is not None:
        y = y + bias
    return y


def tp_mlp(x, w1, b1, w2, b2, *, axis_name: str = MODEL_AXIS,
           activation=jax.nn.gelu, compute_dtype=jnp.bfloat16):
    """Column → activation → row: the Megatron MLP, one psum total.
    w1: (D, mlp/tp) local shard; w2: (mlp/tp, D) local shard."""
    h = column_parallel_dense(x, w1, b1, compute_dtype=compute_dtype)
    h = activation(h).astype(compute_dtype)
    return row_parallel_dense(h, w2, b2, axis_name=axis_name,
                              compute_dtype=compute_dtype)


def tp_self_attention(x, wq, wk, wv, wo, *, num_local_heads: int,
                      head_dim: int, axis_name: str = MODEL_AXIS,
                      seq_axis: Optional[str] = None, causal: bool = True,
                      compute_dtype=jnp.bfloat16,
                      ring_block_k: Optional[int] = None,
                      num_local_kv_heads: Optional[int] = None,
                      window: Optional[int] = None,
                      rope_positions=None,
                      sp_impl: str = "ring",
                      rope_theta: float = 10000.0,
                      rope_scale: float = 1.0):
    """Head-parallel self-attention: each model-axis shard owns
    ``num_local_heads`` heads end to end (qkv column-split by head, local
    attention, output row-split) — one psum per block.  With ``seq_axis``
    set, attention itself runs as a ring over that mesh axis (sequence
    parallelism composing with tensor parallelism).

    x: (B, S_local, D) replicated over 'model'; wq: (D, local_heads·Dh)
    shard; wk/wv: (D, local_kv_heads·Dh) shards; wo: (local_heads·Dh, D)
    shard.  ``num_local_kv_heads`` (default = ``num_local_heads``) gives
    grouped-query attention per shard — each shard keeps whole kv-head
    groups, so GQA composes with head parallelism as long as the global
    kv head count divides by the model-axis size.  ``window``: sliding-
    window masking (requires causal), same semantics as ``ops.attention``.
    ``rope_positions``: (S_local,) GLOBAL token positions of this shard's
    rows — when set, q/k are RoPE-rotated before attention; rotation is
    per-position, so it is valid under the ring too (k blocks arrive
    already rotated by their own global positions).
    ``rope_theta``/``rope_scale``: the context-extension knobs
    (``ops.rope``) — must match the values the checkpoint was trained
    with, as on the Sequential/decode paths.

    ``sp_impl``: which sequence-parallel schedule carries the attend when
    ``seq_axis`` is set — ``"ring"`` (k/v rotation, overlapped, no head
    constraint) or ``"ulysses"`` (two all_to_alls reshard seq<->heads and
    the full-sequence local attend reuses the flash kernel; needs the
    local head count divisible by the seq-axis size).  See
    ``parallel/ulysses.py`` for the trade-off table.
    """
    from .ring import ring_attention
    from .ulysses import ulysses_attention
    from ..ops.attention import attention

    b, s, _ = x.shape
    h, dh = num_local_heads, head_dim
    hkv = num_local_kv_heads if num_local_kv_heads is not None else h

    def proj(w, heads):
        y = column_parallel_dense(x, w, compute_dtype=compute_dtype)
        return y.astype(compute_dtype).reshape(b, s, heads, dh)

    q, k, v = proj(wq, h), proj(wk, hkv), proj(wv, hkv)
    if rope_positions is not None:
        from ..ops.rope import apply_rope
        q = apply_rope(q, rope_positions, rope_theta, rope_scale)
        k = apply_rope(k, rope_positions, rope_theta, rope_scale)
    if seq_axis is not None and sp_impl == "ulysses":
        out = ulysses_attention(q, k, v, seq_axis, causal=causal,
                                window=window)
    elif seq_axis is not None:
        if sp_impl != "ring":
            raise ValueError(f"unknown sp_impl {sp_impl!r} "
                             "(expected 'ring' or 'ulysses')")
        # ring_block_k: blockwise chunking of each rotation's local attend —
        # the long-context memory knob when local shards are large
        out = ring_attention(q, k, v, seq_axis, causal=causal,
                             block_k=ring_block_k, window=window)
    else:
        # dispatcher: the fused Pallas flash kernel on TPU when the local
        # shapes qualify, the XLA reference otherwise
        out = attention(q, k, v, causal=causal, window=window)
    out = out.reshape(b, s, h * dh)
    return row_parallel_dense(out, wo, axis_name=axis_name,
                              compute_dtype=compute_dtype)
