"""Expert parallelism — mixture-of-experts with all_to_all dispatch.

No reference counterpart (SURVEY.md §2.3: expert parallelism absent
upstream).  Experts are sharded across a mesh axis (each device owns
``E / n`` expert MLPs); tokens are routed top-1 with a capacity bound and
physically moved to their expert's device with ``lax.all_to_all`` over ICI,
then moved back and combined with their gate weight — the Switch-Transformer
schedule:

  route (local) → dispatch einsum → all_to_all → expert MLP →
  all_to_all back → combine einsum

Everything is dense einsums against one-hot dispatch masks, so the whole
block is differentiable and jit/scan-safe (static capacity; dropped tokens
contribute zero and pass their residual through untouched in the caller).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

EXPERT_AXIS = "model"  # experts ride the model axis by default


def topk_routing(logits, capacity: int, k: int = 1
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, dict]:
    """Top-k router with per-expert capacity + load-balance statistics.

    logits: (T, E) f32 → (dispatch (T, E, C) one-hot, combine (T, E, C)
    gate-weighted, router stats dict).  ``k=1`` is the Switch-Transformer
    router (combine weight = raw top-1 probability); ``k=2`` is the
    GShard-style variant — each token also goes to its second-choice expert,
    with the two gate weights renormalized to sum to 1.  A choice lands at
    queue slot c of expert e only if fewer than ``capacity`` earlier choices
    (first-choice traffic first) picked e; overflow is dropped (all-zero
    row — the caller's residual connection carries it).

    ``stats`` carries the Switch load-balance ingredients, each (E,):
    ``fraction`` = share of tokens whose *first* choice is e (non-
    differentiable), ``prob`` = mean router probability of e (the
    differentiable path).  Feed (optionally cross-shard-averaged) stats to
    ``load_balance_loss`` and weight the result into the model loss
    (~1e-2) to keep experts alive.  Averaging the *stats* across shards
    before forming the product keeps the loss identical to the
    single-device computation — averaging per-shard products would not.
    """
    e = logits.shape[-1]
    if not 1 <= k <= e:
        raise ValueError(f"router k must be in [1, {e}], got {k}")
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # (T, E)

    # Switch load-balance statistics on the first-choice assignment
    first = jax.nn.one_hot(jnp.argmax(gates, axis=-1), e, dtype=jnp.float32)
    stats = {"fraction": jnp.mean(first, axis=0),
             "prob": jnp.mean(gates, axis=0)}

    # pick the k choices by iterated masked argmax
    choices = []
    masked = gates
    for _ in range(k):
        expert = jnp.argmax(masked, axis=-1)
        gate = jnp.max(masked, axis=-1)
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)
        choices.append((onehot, gate))
        masked = masked * (1.0 - onehot)
    # k=1 keeps the raw probability (Switch); k>1 renormalizes over choices
    denom = (sum(g for _, g in choices) + 1e-9) if k > 1 else 1.0

    dispatch = jnp.zeros(logits.shape + (capacity,), jnp.float32)
    combine = jnp.zeros_like(dispatch)
    counts = jnp.zeros((e,), jnp.float32)  # slots taken by earlier choices
    for onehot, gate in choices:
        pos = jnp.cumsum(onehot, axis=0) * onehot + counts * onehot
        keep = onehot * (pos <= capacity)
        slot = jax.nn.one_hot((pos - 1.0).astype(jnp.int32), capacity,
                              dtype=jnp.float32)                # (T, E, C)
        d = keep[..., None] * slot
        dispatch = dispatch + d
        combine = combine + d * (gate / denom)[:, None, None]
        counts = counts + jnp.sum(keep, axis=0)
    return dispatch, combine, stats


def load_balance_loss(stats: dict) -> jnp.ndarray:
    """Switch load-balance aux ``E · Σ_e f_e · P_e`` from router stats
    (minimized at 1.0 for uniform routing, → E under full collapse).
    Pass globally-averaged stats for a sharding-invariant loss."""
    f, p = stats["fraction"], stats["prob"]
    return f.shape[-1] * jnp.sum(f * p)


def top1_routing(logits, capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 router (back-compat surface): ``topk_routing(k=1)`` without
    the load-balance stats."""
    dispatch, combine, _ = topk_routing(logits, capacity, k=1)
    return dispatch, combine


def moe_mlp(x, router_kernel, w1, b1, w2, b2, *,
            axis_name: str = EXPERT_AXIS, capacity_factor: float = 1.25,
            activation=jax.nn.gelu, compute_dtype=jnp.bfloat16,
            router_top_k: int = 1):
    """Expert-parallel MoE MLP for (B, S, D) inputs inside shard_map.

    ``x`` is replicated (in value) over ``axis_name``; each shard routes only
    its 1/n slice of the tokens, so expert FLOPs and all_to_all bytes are
    paid once per token, not once per shard.  The per-slice outputs reunite
    with a tiled all_gather — the result is replicated in *value* over the
    axis but typed as axis-varying; callers whose outputs must be provably
    replicated reduce later (e.g. a pmean on the scalar loss, as
    ``ParallelTransformerLM`` does).

    router_kernel: (D, E) replicated; w1: (E_local, D, F), b1: (E_local, F),
    w2: (E_local, F, D), b2: (E_local, D) — local expert shards.  Returns
    ``((B, S, D) f32 output, router stats)`` — the output adds to the
    residual stream; the stats (per-expert fraction/prob over this shard's
    token slice, see ``topk_routing``) feed ``load_balance_loss`` after the
    caller pmeans them across shards.  Requires B·S divisible by the axis
    size.  ``router_top_k=2`` enables second-choice routing.
    """
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    e_local = w1.shape[0]
    e_total = n * e_local
    b, s, d = x.shape
    t = b * s
    if t % n:
        raise ValueError(f"token count {t} not divisible by axis size {n}")
    t_loc = t // n
    # GShard capacity convention: k choices per token issue k·T_loc dispatch
    # slots' worth of traffic, so capacity scales with router_top_k — else
    # top-2 silently halves the effective capacity factor
    capacity = max(int(math.ceil(
        capacity_factor * router_top_k * t_loc / e_total)), 1)

    xt = x.reshape(t, d)
    xl = jax.lax.dynamic_slice_in_dim(xt, rank * t_loc, t_loc)  # my slice
    logits = xl.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    dispatch, combine, stats = topk_routing(logits, capacity,
                                            router_top_k)   # (T_loc, E, C)

    # gather my tokens into per-expert buffers and ship each expert's buffer
    # to the device that owns it
    buf = jnp.einsum("td,tec->ecd", xl.astype(jnp.float32), dispatch)
    buf = buf.reshape(n, e_local, capacity, d)
    buf = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0)
    # (n, e_local, C, D): axis 0 is now the *source* device
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d)

    h = jnp.einsum("etd,edf->etf", buf.astype(compute_dtype),
                   w1.astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    h = activation(h + b1[:, None, :]).astype(compute_dtype)
    out = jnp.einsum("etf,efd->etd", h, w2.astype(compute_dtype),
                     preferred_element_type=jnp.float32)
    out = out + b2[:, None, :]

    # return every token to its source device and recombine my slice
    out = out.reshape(e_local, n, capacity, d).transpose(1, 0, 2, 3)
    out = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0)
    # (n, e_local, C, D): axis 0 is now the expert group again
    out = out.reshape(e_total, capacity, d)
    yl = jnp.einsum("ecd,tec->td", out, combine)            # (T_loc, D)

    # reassemble the full token set from the per-shard slices (ships only
    # the 1/n non-zero payload, unlike a zero-padded psum)
    y = jax.lax.all_gather(yl, axis_name, axis=0, tiled=True)
    return y.reshape(b, s, d), stats
