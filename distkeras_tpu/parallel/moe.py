"""Expert parallelism — mixture-of-experts with all_to_all dispatch.

No reference counterpart (SURVEY.md §2.3: expert parallelism absent
upstream).  Experts are sharded across a mesh axis (each device owns
``E / n`` expert MLPs); tokens are routed top-1 with a capacity bound and
physically moved to their expert's device with ``lax.all_to_all`` over ICI,
then moved back and combined with their gate weight — the Switch-Transformer
schedule:

  route (local) → dispatch einsum → all_to_all → expert MLP →
  all_to_all back → combine einsum

Everything is dense einsums against one-hot dispatch masks, so the whole
block is differentiable and jit/scan-safe (static capacity; dropped tokens
contribute zero and pass their residual through untouched in the caller).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

EXPERT_AXIS = "model"  # experts ride the model axis by default


def top1_routing(logits, capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 router with per-expert capacity.

    logits: (T, E) f32 → dispatch (T, E, C) one-hot, combine (T, E, C)
    gate-weighted.  Token t goes to its argmax expert e at queue slot c if
    fewer than ``capacity`` earlier tokens chose e; otherwise it is dropped
    (all-zero row — the caller's residual connection carries it).
    """
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(gates, axis=-1)                       # (T,)
    gate = jnp.max(gates, axis=-1)                            # (T,)
    onehot = jax.nn.one_hot(expert, logits.shape[-1],
                            dtype=jnp.float32)                # (T, E)
    pos = jnp.cumsum(onehot, axis=0) * onehot                 # 1-based slot
    onehot = onehot * (pos <= capacity)
    slot = jax.nn.one_hot((pos - 1.0).astype(jnp.int32), capacity,
                          dtype=jnp.float32)                  # (T, E, C)
    dispatch = onehot[..., None] * slot
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_mlp(x, router_kernel, w1, b1, w2, b2, *,
            axis_name: str = EXPERT_AXIS, capacity_factor: float = 1.25,
            activation=jax.nn.gelu, compute_dtype=jnp.bfloat16):
    """Expert-parallel MoE MLP for (B, S, D) inputs inside shard_map.

    ``x`` is replicated (in value) over ``axis_name``; each shard routes only
    its 1/n slice of the tokens, so expert FLOPs and all_to_all bytes are
    paid once per token, not once per shard.  The per-slice outputs reunite
    with a tiled all_gather — the result is replicated in *value* over the
    axis but typed as axis-varying; callers whose outputs must be provably
    replicated reduce later (e.g. a pmean on the scalar loss, as
    ``ParallelTransformerLM`` does).

    router_kernel: (D, E) replicated; w1: (E_local, D, F), b1: (E_local, F),
    w2: (E_local, F, D), b2: (E_local, D) — local expert shards.  Returns
    (B, S, D) f32 (add to the residual stream in the caller).  Requires
    B·S divisible by the axis size.
    """
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    e_local = w1.shape[0]
    e_total = n * e_local
    b, s, d = x.shape
    t = b * s
    if t % n:
        raise ValueError(f"token count {t} not divisible by axis size {n}")
    t_loc = t // n
    capacity = max(int(math.ceil(capacity_factor * t_loc / e_total)), 1)

    xt = x.reshape(t, d)
    xl = jax.lax.dynamic_slice_in_dim(xt, rank * t_loc, t_loc)  # my slice
    logits = xl.astype(jnp.float32) @ router_kernel.astype(jnp.float32)
    dispatch, combine = top1_routing(logits, capacity)      # (T_loc, E, C)

    # gather my tokens into per-expert buffers and ship each expert's buffer
    # to the device that owns it
    buf = jnp.einsum("td,tec->ecd", xl.astype(jnp.float32), dispatch)
    buf = buf.reshape(n, e_local, capacity, d)
    buf = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0)
    # (n, e_local, C, D): axis 0 is now the *source* device
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, n * capacity, d)

    h = jnp.einsum("etd,edf->etf", buf.astype(compute_dtype),
                   w1.astype(compute_dtype),
                   preferred_element_type=jnp.float32)
    h = activation(h + b1[:, None, :]).astype(compute_dtype)
    out = jnp.einsum("etf,efd->etd", h, w2.astype(compute_dtype),
                     preferred_element_type=jnp.float32)
    out = out + b2[:, None, :]

    # return every token to its source device and recombine my slice
    out = out.reshape(e_local, n, capacity, d).transpose(1, 0, 2, 3)
    out = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0)
    # (n, e_local, C, D): axis 0 is now the expert group again
    out = out.reshape(e_total, capacity, d)
    yl = jnp.einsum("ecd,tec->td", out, combine)            # (T_loc, D)

    # reassemble the full token set from the per-shard slices (ships only
    # the 1/n non-zero payload, unlike a zero-padded psum)
    y = jax.lax.all_gather(yl, axis_name, axis=0, tiled=True)
    return y.reshape(b, s, d)
