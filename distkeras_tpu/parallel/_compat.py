"""jax version compatibility for the parallel stack.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` around jax 0.6; every call site here uses the
keyword form (``mesh=``/``in_specs=``/``out_specs=``), which both accept.
Resolve once so the modules under ``parallel/`` run on either.
"""

import jax

if hasattr(jax, "typeof"):  # the vma-typed shard_map generation
    shard_map = jax.shard_map
    pcast = jax.lax.pcast
    axis_size = jax.lax.axis_size

    def vma_of(x):
        """Mesh axes ``x`` varies over (empty tuple when untyped)."""
        return getattr(jax.typeof(x), "vma", ()) or ()
else:  # jax < 0.6: no vma typing — every value is implicitly varying,
    # pcast has nothing to record, and shard_map lives in experimental
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        # Replication checking stays ON by default: check_rep=False also
        # disables the psum-aware transpose, which CHANGES gradients of
        # pure-jnp bodies (ZeRO/FSDP paths regress).  But the old checker
        # has no rule for pallas_call — bodies with Pallas kernels (the
        # vma plumbing in ops/_vma.py is how the NEW checker passes them)
        # raise NotImplementedError at trace time, and only those fall
        # back to the unchecked form.
        checked = _shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
        unchecked = _shard_map(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=False)

        def call(*args):
            try:
                return checked(*args)
            except NotImplementedError:
                # no replication rule for pallas_call in the old checker
                return unchecked(*args)
            except ValueError as e:
                if "check_rep=False" not in str(e):
                    raise
                # out_specs replication the old checker can't infer
                return unchecked(*args)

        return call

    def pcast(x, axis_name, *, to="varying"):
        return x

    def vma_of(x):
        return ()

    def axis_size(axis_name):
        # psum of a unit constant constant-folds to the bound axis size
        # (a Python int, so shape math downstream stays static)
        return jax.lax.psum(1, axis_name)
