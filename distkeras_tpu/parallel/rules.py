"""Per-algorithm update rules as pure pytree functions.

These are the *semantic core* of each distributed optimization algorithm,
factored out of execution so they can be (a) unit-tested against closed-form
cases (SURVEY.md §4's "test the update rule as a pure function"), and (b)
shared verbatim between the SPMD collective path (``spmd.py``) and the
host-side async parameter-server path (``parameter_servers.py``) — both
execution engines apply exactly these rules.

Reference semantics being preserved:
 - delta commit:      ``parameter_servers.py :: DeltaParameterServer``
                      (center += delta)
 - ADAG normalize:    ``parameter_servers.py :: ADAGParameterServer``
                      (accumulated deltas normalized before apply)
 - elastic term:      ``workers.py :: AEASGDWorker`` (ρ-scaled difference,
                      subtracted locally and committed to the center)
 - staleness scaling: ``parameter_servers.py :: DynSGDParameterServer``
                      (center += delta / (staleness + 1))
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map


def tree_sub(a, b):
    return tmap(jnp.subtract, a, b)


def tree_add(a, b):
    return tmap(jnp.add, a, b)


def tree_scale(a, s):
    return tmap(lambda x: x * s, a)


def delta_commit(center, delta):
    """DOWNPOUR-style raw delta apply: center += delta."""
    return tree_add(center, delta)


def adag_commit(center, summed_delta, num_commits):
    """ADAG: deltas accumulated across workers, normalized by commit count
    before applying — the bulk-synchronous form is an all-reduce *mean* of
    window deltas."""
    return tree_add(center, tree_scale(summed_delta, 1.0 / num_commits))


def elastic_difference(local, center, alpha):
    """EASGD elastic force α·(x − x̃). ``alpha`` is the elastic coefficient
    (paper: α = η·ρ; the reference exposes ``rho`` and ``learning_rate``)."""
    return tmap(lambda x, c: alpha * (x - c), local, center)


def easgd_worker_update(local, elastic):
    """Worker side of the elastic exchange: x ← x − e."""
    return tree_sub(local, elastic)


def easgd_center_update(center, summed_elastic):
    """Center side: x̃ ← x̃ + Σᵢ eᵢ (sum over workers' elastic terms)."""
    return tree_add(center, summed_elastic)


def dynsgd_commit(center, delta, staleness):
    """DynSGD staleness-aware apply: center += delta / (staleness + 1)."""
    return tmap(lambda c, d: c + d / (staleness + 1.0), center, delta)


def average_trees(trees):
    """Average a list of pytrees (AveragingTrainer's one-shot model average;
    reference: ``trainers.py :: AveragingTrainer.average_models``)."""
    n = len(trees)
    out = trees[0]
    for t in trees[1:]:
        out = tree_add(out, t)
    return tree_scale(out, 1.0 / n)
