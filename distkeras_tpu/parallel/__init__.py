from .mesh import (WORKER_AXIS, get_mesh, initialize, replicated,
                   worker_sharded, put_replicated, put_worker_sharded)
from .spmd import SPMDEngine, DistState, shape_epoch_data
from .ring import SEQ_AXIS, ring_attention, ring_self_attention
from .ulysses import ulysses_attention, ulysses_self_attention
from .tp import (MODEL_AXIS, column_parallel_dense, row_parallel_dense,
                 tp_mlp, tp_self_attention)
from .moe import load_balance_loss, moe_mlp, top1_routing, topk_routing
from .pipeline import STAGE_AXIS, pipeline_apply
from .transformer import ParallelTransformerLM
from .pp_transformer import PipelineTransformerLM
from . import rules

__all__ = [
    "WORKER_AXIS", "get_mesh", "initialize", "replicated", "worker_sharded",
    "put_replicated", "put_worker_sharded",
    "SPMDEngine", "DistState", "shape_epoch_data", "rules",
    "SEQ_AXIS", "ring_attention", "ring_self_attention",
    "ulysses_attention", "ulysses_self_attention",
    "MODEL_AXIS", "column_parallel_dense", "row_parallel_dense",
    "tp_mlp", "tp_self_attention", "moe_mlp", "top1_routing",
    "topk_routing", "load_balance_loss",
    "STAGE_AXIS", "pipeline_apply", "ParallelTransformerLM",
    "PipelineTransformerLM",
]
