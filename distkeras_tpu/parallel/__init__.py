from .mesh import (WORKER_AXIS, get_mesh, initialize, replicated,
                   worker_sharded, put_replicated, put_worker_sharded)
from .spmd import SPMDEngine, DistState, shape_epoch_data
from . import rules

__all__ = [
    "WORKER_AXIS", "get_mesh", "initialize", "replicated", "worker_sharded",
    "put_replicated", "put_worker_sharded",
    "SPMDEngine", "DistState", "shape_epoch_data", "rules",
]
