from .mesh import (WORKER_AXIS, get_mesh, initialize, replicated,
                   worker_sharded, put_replicated, put_worker_sharded)
from .spmd import SPMDEngine, DistState, shape_epoch_data
from .ring import SEQ_AXIS, ring_attention, ring_self_attention
from . import rules

__all__ = [
    "WORKER_AXIS", "get_mesh", "initialize", "replicated", "worker_sharded",
    "put_replicated", "put_worker_sharded",
    "SPMDEngine", "DistState", "shape_epoch_data", "rules",
    "SEQ_AXIS", "ring_attention", "ring_self_attention",
]
