"""ParallelTransformerLM — one train step composing dp × tp × sp (+ ep).

The integration point of the model-parallel layer (no reference counterpart;
SURVEY.md §2.3): a decoder-only LM whose single jitted train step shards

 - the batch over the 'data' mesh axis (data parallelism),
 - the sequence over the 'seq' axis (ring attention, ``ring.py``),
 - attention heads + MLP/expert weights over the 'model' axis
   (Megatron tensor parallelism, ``tp.py``; Switch expert parallelism,
   ``moe.py``),

inside one ``shard_map`` over the full mesh.  Gradients come out correct
without hand-written reductions: jax's varying-axes machinery inserts the
psum transposes for replicated params automatically, and sharded params keep
their 'model'-varying grads aligned with their shards.  The loss is the
global token mean (psum over data+seq of local sums).

This is the program ``__graft_entry__.dryrun_multichip`` compiles over an
n-device mesh.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .tp import tp_mlp, tp_self_attention
from .moe import moe_mlp

tmap = jax.tree_util.tree_map


class ParallelTransformerLM:
    """Causal LM over a ('data', 'seq', 'model') mesh."""

    def __init__(self, vocab_size: int, seq_len: int, d_model: int,
                 num_heads: int, num_layers: int, mlp_dim: int,
                 mesh: Mesh, *, moe_layers: Tuple[int, ...] = (),
                 num_experts: Optional[int] = None,
                 capacity_factor: float = 2.0,
                 router_top_k: int = 1,
                 router_aux_weight: float = 1e-2,
                 compute_dtype=jnp.bfloat16, remat: bool = False,
                 ring_block_k: Optional[int] = None,
                 sp_impl: str = "ring", fused_ce: bool = False,
                 num_kv_heads: Optional[int] = None,
                 attention_window: Optional[int] = None,
                 positional: str = "learned",
                 rope_theta: float = 10000.0, rope_scale: float = 1.0,
                 data_axis: str = "data", seq_axis: str = "seq",
                 model_axis: str = "model"):
        self.vocab_size = vocab_size
        self.seq_len = seq_len
        self.d_model = d_model
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.mlp_dim = mlp_dim
        self.mesh = mesh
        self.moe_layers = tuple(moe_layers)
        self.capacity_factor = capacity_factor
        # Switch load-balance recipe: the aux term (topk_routing) keeps the
        # router from collapsing onto one expert; ~1e-2 is the paper weight
        self.router_top_k = int(router_top_k)
        self.router_aux_weight = float(router_aux_weight)
        self.compute_dtype = compute_dtype
        self.remat = bool(remat)
        # blockwise chunking of ring attention's local attend (memory knob
        # for long per-device sequence shards); None = unchunked
        self.ring_block_k = ring_block_k
        self.axes = (data_axis, seq_axis, model_axis)
        self.tp = mesh.shape[model_axis]
        self.sp = mesh.shape[seq_axis]
        self.dp = mesh.shape[data_axis]
        if num_heads % self.tp:
            raise ValueError(f"num_heads {num_heads} % tp {self.tp} != 0")
        if sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be 'ring' or 'ulysses', got "
                             f"{sp_impl!r}")
        # ulysses reshards the model-local heads over the seq axis: two
        # all_to_alls + a full-sequence flash attend (parallel/ulysses.py)
        self.sp_impl = sp_impl
        # fused_ce: per-token loss via the streaming Pallas kernel
        # (ops/fused_ce.py) instead of a materialized (T, V) log_softmax —
        # the HBM win grows with vocab size
        self.fused_ce = bool(fused_ce)
        if sp_impl == "ulysses" and (num_heads // self.tp) % self.sp:
            raise ValueError(
                f"sp_impl='ulysses' needs local head count "
                f"{num_heads // self.tp} (num_heads/tp) divisible by sp "
                f"{self.sp}; use sp_impl='ring' for this shape")
        # GQA over tensor parallelism: every shard keeps whole kv-head
        # groups, so the kv head count must divide both H and the tp size
        self.num_kv_heads = (int(num_kv_heads) if num_kv_heads is not None
                             else num_heads)
        if num_heads % self.num_kv_heads:
            raise ValueError(f"num_heads {num_heads} % num_kv_heads "
                             f"{self.num_kv_heads} != 0")
        if self.num_kv_heads % self.tp:
            raise ValueError(f"num_kv_heads {self.num_kv_heads} % tp "
                             f"{self.tp} != 0 (each model shard needs whole "
                             "kv heads)")
        from ..ops.attention import validate_window
        self.attention_window = validate_window(attention_window,
                                                causal=True)
        if positional not in ("learned", "rope"):
            raise ValueError(f"positional must be 'learned' or 'rope', "
                             f"got {positional!r}")
        self.positional = positional
        if positional == "rope":
            from ..ops.rope import validate_rope_dim, validate_rope_scaling
            validate_rope_dim(d_model // num_heads)
            self.rope_theta, self.rope_scale = validate_rope_scaling(
                rope_theta, rope_scale)
        else:
            self.rope_theta, self.rope_scale = float(rope_theta), float(
                rope_scale)
        if mlp_dim % self.tp:
            raise ValueError(f"mlp_dim {mlp_dim} % tp {self.tp} != 0")
        if seq_len % self.sp:
            raise ValueError(f"seq_len {seq_len} % sp {self.sp} != 0")
        self.num_experts = (num_experts if num_experts is not None
                            else self.tp)
        if self.moe_layers and self.num_experts % self.tp:
            raise ValueError("num_experts must divide over the model axis")
        self.head_dim = d_model // num_heads

    # -- params + specs -------------------------------------------------------
    def _layer_shapes(self, i: int):
        d, f, hd = self.d_model, self.mlp_dim, self.num_heads * self.head_dim
        hd_kv = self.num_kv_heads * self.head_dim
        _, _, model = self.axes
        shapes = {
            "ln1": ((d,), P()),
            "ln2": ((d,), P()),
            "wq": ((d, hd), P(None, model)),
            "wk": ((d, hd_kv), P(None, model)),
            "wv": ((d, hd_kv), P(None, model)),
            "wo": ((hd, d), P(model, None)),
        }
        if i in self.moe_layers:
            e = self.num_experts
            shapes.update({
                "router": ((d, e), P()),
                "w1": ((e, d, f), P(model, None, None)),
                "b1": ((e, f), P(model, None)),
                "w2": ((e, f, d), P(model, None, None)),
                "b2": ((e, d), P(model, None)),
            })
        else:
            shapes.update({
                "w1": ((d, f), P(None, model)),
                "b1": ((f,), P(model)),
                "w2": ((f, d), P(model, None)),
                "b2": ((d,), P()),
            })
        return shapes

    def _shapes_and_specs(self):
        d = self.d_model
        shapes: dict = {
            "embed": ((self.vocab_size, d), P()),
            "ln_f": ((d,), P()),
            "head": ((d, self.vocab_size), P()),
            "layers": [self._layer_shapes(i) for i in range(self.num_layers)],
        }
        if self.positional == "learned":  # rope has no additive table
            shapes["pos"] = ((self.seq_len, d), P())
        split = lambda take: tmap(lambda sp: sp[take], shapes,
                                  is_leaf=lambda x: isinstance(x, tuple)
                                  and len(x) == 2 and isinstance(x[0], tuple))
        return split(0), split(1)

    def param_specs(self):
        return self._shapes_and_specs()[1]

    def init(self, rng) -> Any:
        """Initialize params directly into their mesh shardings.

        LN scales → ones, biases → zeros, embeddings/pos → small normal,
        matmul weights → normal / sqrt(fan_in).
        """
        shapes, specs = self._shapes_and_specs()
        is_shape = lambda x: (isinstance(x, tuple)
                              and all(isinstance(d, int) for d in x))
        flat, tree = jax.tree_util.tree_flatten_with_path(
            shapes, is_leaf=is_shape)
        flat_specs = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        rngs = jax.random.split(rng, len(flat))
        leaves = []
        for k, (path, shape), spec in zip(rngs, flat, flat_specs):
            name = path[-1].key if hasattr(path[-1], "key") else ""
            if name.startswith("ln"):
                arr = jnp.ones(shape, jnp.float32)
            elif name.startswith("b"):
                arr = jnp.zeros(shape, jnp.float32)
            elif name in ("embed", "pos"):
                arr = 0.02 * jax.random.normal(k, shape, jnp.float32)
            else:
                arr = (jax.random.normal(k, shape, jnp.float32)
                       / math.sqrt(max(shape[-2] if len(shape) > 1
                                       else shape[0], 1)))
            leaves.append(jax.device_put(
                arr, NamedSharding(self.mesh, spec)))
        return jax.tree_util.tree_unflatten(tree, leaves)

    # -- forward --------------------------------------------------------------
    def _forward(self, params, tokens):
        """Local forward inside shard_map: tokens (B_loc, S_loc) int32 →
        (logits (B_loc, S_loc, V) f32, per-MoE-layer router stats — this
        shard's token slice; empty list for a dense stack)."""
        data_axis, seq_axis, model_axis = self.axes
        cdt = self.compute_dtype
        s_loc = tokens.shape[1]
        seq_idx = jax.lax.axis_index(seq_axis)

        x = params["embed"].astype(cdt)[tokens]
        if self.positional == "learned":
            pos = jax.lax.dynamic_slice_in_dim(params["pos"],
                                               seq_idx * s_loc, s_loc)
            x = x + pos.astype(cdt)
        # rope: rotation happens on q/k inside each block (global positions)
        rope_pos = (seq_idx * s_loc + jnp.arange(s_loc)
                    if self.positional == "rope" else None)

        def ln(scale, h):
            h32 = h.astype(jnp.float32)
            mu = jnp.mean(h32, axis=-1, keepdims=True)
            var = jnp.var(h32, axis=-1, keepdims=True)
            return ((h32 - mu) * jax.lax.rsqrt(var + 1e-5)
                    * scale).astype(cdt)

        def block(i):
            def body(x, lp):
                h = ln(lp["ln1"], x)
                attn = tp_self_attention(
                    h, lp["wq"], lp["wk"], lp["wv"], lp["wo"],
                    num_local_heads=self.num_heads // self.tp,
                    head_dim=self.head_dim, axis_name=model_axis,
                    seq_axis=seq_axis, causal=True, compute_dtype=cdt,
                    ring_block_k=self.ring_block_k,
                    num_local_kv_heads=self.num_kv_heads // self.tp,
                    window=self.attention_window,
                    rope_positions=rope_pos, sp_impl=self.sp_impl,
                    rope_theta=self.rope_theta,
                    rope_scale=self.rope_scale)
                x = x + attn.astype(cdt)
                h = ln(lp["ln2"], x)
                stats = None
                if i in self.moe_layers:
                    # token slices route per model shard and all_gather back
                    # inside moe_mlp (value-replicated over 'model')
                    y, stats = moe_mlp(h, lp["router"], lp["w1"], lp["b1"],
                                       lp["w2"], lp["b2"],
                                       axis_name=model_axis,
                                       capacity_factor=self.capacity_factor,
                                       compute_dtype=cdt,
                                       router_top_k=self.router_top_k)
                else:
                    y = tp_mlp(h, lp["w1"], lp["b1"], lp["w2"], lp["b2"],
                               axis_name=model_axis, compute_dtype=cdt)
                return x + y.astype(cdt), stats

            # remat: recompute block activations in the backward pass instead
            # of keeping them in HBM — the long-context memory/FLOPs trade
            return jax.checkpoint(body) if self.remat else body

        router_stats = []
        for i, lp in enumerate(params["layers"]):
            x, stats = block(i)(x, lp)
            if stats is not None:
                router_stats.append(stats)

        x = ln(params["ln_f"], x)
        logits = jax.lax.dot_general(
            x.astype(cdt), params["head"].astype(cdt),
            (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return logits, router_stats

    def _loss(self, params, tokens, labels):
        from .moe import load_balance_loss
        data_axis, seq_axis, model_axis = self.axes
        logits, router_stats = self._forward(params, tokens)
        if self.fused_ce:
            from ..ops.fused_ce import fused_softmax_cross_entropy
            losses = fused_softmax_cross_entropy(
                logits.reshape(-1, self.vocab_size),
                labels.reshape(-1).astype(jnp.int32))
            local_sum = jnp.sum(losses)
            local_cnt = jnp.asarray(losses.size, jnp.float32)
        else:
            logp = jax.nn.log_softmax(logits, axis=-1)
            picked = jnp.take_along_axis(
                logp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
            local_sum = -jnp.sum(picked)
            local_cnt = jnp.asarray(picked.size, jnp.float32)
        total = jax.lax.psum(local_sum, (data_axis, seq_axis))
        count = jax.lax.psum(local_cnt, (data_axis, seq_axis))
        # scalar pmean over 'model': a no-op in value (every model shard
        # computes the same loss) that makes the replication provable — the
        # MoE all_gather leaves activations typed model-varying
        loss = jax.lax.pmean(total / count, model_axis)
        for stats in router_stats:
            # every (data, seq, model) shard routes an equal-sized disjoint
            # token slice: pmean the STATS first, then form the f·P product
            # once — the loss is then identical on any mesh shape
            # (averaging per-shard products would not be)
            global_stats = jax.tree_util.tree_map(
                lambda v: jax.lax.pmean(v, (data_axis, seq_axis,
                                            model_axis)), stats)
            loss = loss + (self.router_aux_weight
                           * load_balance_loss(global_stats))
        return loss

    # -- train step -----------------------------------------------------------
    def compile_train_step(self, optimizer: optax.GradientTransformation,
                           params, zero: bool = False, fsdp: bool = False):
        """Build (opt_state, jitted step): step(params, opt, tokens, labels)
        -> (params, opt, loss).  tokens/labels are (B, S) int32 sharded
        ``P('data', 'seq')``.  ``zero=True`` ZeRO-1-shards the optimizer
        state over the data axis (same update math, mu/nu HBM / dp);
        ``fsdp=True`` goes further to ZeRO-3 — params AND moments live
        data-axis-sharded at rest, gathered per step by GSPMD (see
        ``train_step.build_train_step``; supersedes ``zero``)."""
        from .train_step import build_train_step
        data_axis, seq_axis, _ = self.axes
        return build_train_step(self.mesh, self._loss, self.param_specs(),
                                P(data_axis, seq_axis), optimizer, params,
                                zero_axis=data_axis if zero else None,
                                fsdp_axis=data_axis if fsdp else None)

    def batch_sharding(self) -> NamedSharding:
        data_axis, seq_axis, _ = self.axes
        return NamedSharding(self.mesh, P(data_axis, seq_axis))
