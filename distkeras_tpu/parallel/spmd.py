"""SPMD execution engine for the distributed trainers.

Reference architecture being replaced (SURVEY.md §2.4, §3.1): N Spark workers
train locally and exchange full weight deltas with a driver parameter server
over TCP/pickle every ``communication_window`` minibatches.  Here the same
algorithm semantics execute as a bulk-synchronous SPMD program over a
``Mesh(('workers',))``:

 - "pull center"      → read the replicated center params (no transfer at all)
 - "commit delta"     → ``lax.psum`` of window deltas over the ICI ring
 - "PS apply rule"    → the pure functions in ``rules.py`` applied in-graph
 - per-worker state   → pytrees with a leading 'workers' axis, sharded
                        ``P('workers')`` so each chip owns exactly its worker

One *round* = ``communication_window`` local minibatch steps (an in-graph
``lax.scan``) + one collective exchange.  A whole epoch of rounds is itself a
``lax.scan``, so an epoch is a single XLA program: zero Python dispatch, zero
host↔device traffic between rounds (vs. the reference's per-window pickle of
the full weight vector through the driver).

Async-semantics note: XLA is bulk-synchronous, so true hogwild interleaving is
not representable on the ICI path.  Each algorithm keeps its *update rule*
exactly (ADAG normalization, elastic term, staleness scaling) while commits
within a round are emulated as a deterministic serialized order (DynSGD's
staleness = position in a per-round rotation).  The semantically-exact
thread-async execution lives in ``distkeras_tpu.parameter_servers`` (host/DCN
path); both engines share ``rules.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import _compat

from ..core.model import Sequential
from ..core.losses import get_loss
from ..core import optimizers as opt_lib
from . import rules
from .mesh import WORKER_AXIS, replicated, worker_sharded

tmap = jax.tree_util.tree_map

class DistState(NamedTuple):
    """Distributed training state.

    center:    replicated params pytree (the PS "center" model)
    local:     per-worker params, leaves stacked on a leading 'workers' axis
    opt_state: per-worker optimizer state, same stacking
    round_idx: int32 scalar — the PS clock (reference:
               ``ParameterServer.next_update`` counter)
    """
    center: Any
    local: Any
    opt_state: Any
    round_idx: jnp.ndarray


class SPMDEngine:
    """Builds and runs the jitted per-epoch program for one algorithm."""

    def __init__(self, model: Sequential, loss, worker_optimizer,
                 mesh: Mesh, algorithm: str,
                 communication_window: int = 5,
                 learning_rate: Optional[float] = None,
                 alpha: Optional[float] = None,
                 lr_schedule=None, schedule_steps: Optional[int] = None,
                 gradient_accumulation: int = 1,
                 gradient_clip_norm=None,
                 packed: bool = False):
        self.model = model
        self.loss_fn = get_loss(loss)
        self.mesh = mesh
        self.algorithm = algorithm
        self.window = int(communication_window)
        self.num_workers = int(mesh.devices.size)
        self.alpha = alpha
        self.optimizer = opt_lib.get_optimizer(worker_optimizer, learning_rate)
        self.lr_schedule = lr_schedule
        self.schedule_steps = schedule_steps
        self.gradient_accumulation = int(gradient_accumulation)
        self.gradient_clip_norm = gradient_clip_norm
        # packed=True: the epoch/round programs additionally scan a
        # segment-ids array (sequence packing, data/packing.py) threaded
        # into the masked step's forward so attention keeps per-document
        # isolation — the distributed twin of SingleTrainer(segment_col=…)
        self.packed = bool(packed)
        self.tx = None  # built in init_state (needs params for masking)
        self._epoch_fn = None
        self._round_step = None

    # -- state --------------------------------------------------------------
    def init_state(self, rng, input_shape, initial_params=None) -> DistState:
        params = self.model.init(rng, input_shape)
        if initial_params is not None:
            params = initial_params
        self.tx = opt_lib.build_tx(
            self.optimizer, params, lr_schedule=self.lr_schedule,
            total_steps=self.schedule_steps,
            gradient_accumulation=self.gradient_accumulation,
            gradient_clip_norm=self.gradient_clip_norm)
        n = self.num_workers
        # every worker starts from the same center (reference: initial pull)
        local = tmap(lambda x: jnp.broadcast_to(x, (n,) + x.shape), params)
        opt_state = jax.vmap(self.tx.init)(local)
        center = jax.device_put(params, replicated(self.mesh))
        local = tmap(lambda x: jax.device_put(x, worker_sharded(self.mesh)),
                     local)
        opt_state = tmap(
            lambda x: jax.device_put(x, worker_sharded(self.mesh)), opt_state)
        return DistState(center, local, opt_state,
                         jnp.zeros((), jnp.int32))

    def put_state(self, state: DistState) -> DistState:
        """Re-apply mesh shardings to a host-side state pytree (checkpoint
        restore path — the leaves arrive as numpy arrays)."""
        ws = worker_sharded(self.mesh)
        center = jax.device_put(state.center, replicated(self.mesh))
        local = tmap(lambda x: jax.device_put(x, ws), state.local)
        opt_state = tmap(lambda x: jax.device_put(x, ws), state.opt_state)
        # round_idx may arrive as a live single-device jax scalar (orbax
        # sharded restore): pull it to host so the fresh array doesn't pin
        # a stale placement into the jitted epoch's device set
        return DistState(center, local, opt_state,
                         jnp.asarray(jax.device_get(state.round_idx),
                                     jnp.int32))

    # -- the per-round SPMD body ---------------------------------------------
    def _local_window(self, params, opt_state, xw, yw, mw, rng, sw=None):
        """Run ``window`` minibatch steps on one worker's shard (in-graph).

        ``mw``: (window, batch) per-example weights — 1 for real rows, 0 for
        the wrap-padding ``shape_epoch_data`` adds to fill the tail round.
        ``sw`` (packed engines): (window, batch, S) segment ids threaded
        into the forward.  Returns the example-weighted loss sum and the
        weight sum so the caller can form an exact mean over *real*
        examples only.
        """
        from ..core.train import make_masked_step
        step = make_masked_step(self.model, self.loss_fn, self.tx)
        packed = sw is not None

        def body(carry, inp):
            p, s, key = carry
            x, y, seg, w = inp if packed else inp[:2] + (None,) + inp[2:]
            key, sub = jax.random.split(key)
            p, s, l, wsum = step(p, s, x, y, w, sub, seg)
            return (p, s, key), (l, wsum)

        xs = (xw, yw, sw, mw) if packed else (xw, yw, mw)
        (params, opt_state, _), (losses, wsums) = jax.lax.scan(
            body, (params, opt_state, rng), xs)
        return params, opt_state, jnp.sum(losses * wsums), jnp.sum(wsums)

    def _sync_stats(self, new_p, center):
        """psum-mean each worker's EMA'd BatchNorm stats and write the mean
        into both the worker params and the center, so (a) eval on the center
        model uses real running stats and (b) the stats leaves contribute
        exactly zero to every delta/elastic exchange below (worker == center
        ⇒ tree_sub is 0 there, and each commit rule adds 0)."""
        n = self.num_workers
        out_p, out_c = [], []
        for p, c in zip(new_p, center):
            if isinstance(p, dict) and "stats" in p:
                mean = tmap(lambda v: jax.lax.psum(v, WORKER_AXIS) / n,
                            p["stats"])
                # worker-side copy must stay device-varying for the
                # P(WORKER_AXIS) out_spec; the center copy stays unvarying
                p = {**p, "stats": tmap(
                    lambda v: _compat.pcast(v, WORKER_AXIS, to="varying"),
                    mean)}
                c = {**c, "stats": mean}
            out_p.append(p)
            out_c.append(c)
        return out_p, out_c

    def _make_round_fn(self) -> Callable:
        n = self.num_workers
        algo = self.algorithm
        alpha = self.alpha

        packed = self.packed

        def round_fn(center, local, opt_state, round_idx, xw, yw, *rest):
            # Block shapes inside shard_map: local/opt_state leaves and the
            # rng carry a leading worker axis of size 1; the batch data is
            # (window, workers=1, batch, ...) — squeeze the *worker* axis in
            # each (xw[:, 0], NOT xw[0]: that would squeeze the window axis
            # and silently train on only the first batch of every window).
            (sw, mw, rngs) = rest if packed else (None,) + rest
            squeeze = lambda t: tmap(lambda v: v[0], t)
            local_p = squeeze(local)
            opt_s = squeeze(opt_state)
            x = xw[:, 0]
            y = yw[:, 0]
            m = mw[:, 0]
            s = sw[:, 0] if packed else None
            rng = rngs[0]

            if algo in ("adag", "downpour", "dynsgd"):
                # "pull": start from the replicated center; mark it
                # device-varying so the per-worker scan carry typechecks.
                start = tmap(
                    lambda v: _compat.pcast(v, WORKER_AXIS, to="varying"),
                    center)
            else:  # EASGD family + 'local' keep persistent local params
                start = local_p
            new_p, new_s, loss_sum, wsum = self._local_window(
                start, opt_s, x, y, m, rng, s)
            if algo != "local" and self.model.has_stats():
                # 'local' = independent training: per-worker stats persist
                new_p, center = self._sync_stats(new_p, center)

            if algo == "adag":
                delta = rules.tree_sub(new_p, center)
                summed = tmap(lambda d: jax.lax.psum(d, WORKER_AXIS), delta)
                center = rules.adag_commit(center, summed, n)
            elif algo == "downpour":
                delta = rules.tree_sub(new_p, center)
                summed = tmap(lambda d: jax.lax.psum(d, WORKER_AXIS), delta)
                center = rules.delta_commit(center, summed)
            elif algo == "dynsgd":
                # Serialized-commit emulation: within a round, worker w's
                # commit lands after ``order`` earlier commits, where the
                # order rotates every round — its delta is scaled by
                # 1/(staleness+1) exactly as DynSGDParameterServer does.
                w = jax.lax.axis_index(WORKER_AXIS)
                order = jnp.mod(w + round_idx, n).astype(jnp.float32)
                delta = rules.tree_sub(new_p, center)
                scaled = rules.dynsgd_commit(
                    tmap(jnp.zeros_like, center), delta, order)
                summed = tmap(lambda d: jax.lax.psum(d, WORKER_AXIS), scaled)
                center = rules.tree_add(center, summed)
            elif algo == "local":
                # Independent per-worker training (AveragingTrainer /
                # EnsembleTrainer): no exchange; center untouched.
                pass
            elif algo in ("aeasgd", "eamsgd"):
                e = rules.elastic_difference(new_p, center, alpha)
                new_p = rules.easgd_worker_update(new_p, e)
                summed = tmap(lambda d: jax.lax.psum(d, WORKER_AXIS), e)
                center = rules.easgd_center_update(center, summed)
            else:
                raise ValueError(f"unknown algorithm {algo!r}")

            # exact mean over real (unpadded) examples across all workers
            mean_loss = (jax.lax.psum(loss_sum, WORKER_AXIS)
                         / jnp.maximum(jax.lax.psum(wsum, WORKER_AXIS), 1.0))
            unsqueeze = lambda t: tmap(lambda v: v[None], t)
            return (center, unsqueeze(new_p), unsqueeze(new_s), mean_loss)

        return round_fn

    # -- epoch program -------------------------------------------------------
    def _shmapped_round(self) -> Callable:
        """The single shard_map'd round program — the one contract both the
        scanned epoch and the streaming path execute."""
        data_spec = (P(None, WORKER_AXIS),) * (4 if self.packed else 3)
        return _compat.shard_map(
            self._make_round_fn(),
            mesh=self.mesh,
            in_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P())
            + data_spec + (P(WORKER_AXIS),),
            out_specs=(P(), P(WORKER_AXIS), P(WORKER_AXIS), P()),
        )

    @staticmethod
    def _run_round(shmapped, state: DistState, data, rngs):
        """One round: fold the per-worker keys with the round clock, execute,
        re-wrap the state (shared by epoch scan and streaming).  ``data`` =
        (x, y, m) or (x, y, seg, m) on the packed engine."""
        keys = jax.vmap(
            lambda k: jax.random.fold_in(k, state.round_idx))(rngs)
        center, local, opt_state, loss = shmapped(
            state.center, state.local, state.opt_state, state.round_idx,
            *data, keys)
        return (DistState(center, local, opt_state, state.round_idx + 1),
                loss)

    def _build_epoch_fn(self) -> Callable:
        shmapped = self._shmapped_round()

        def epoch(state: DistState, xb, yb, *rest):
            # xb, yb, [sb,] mb: (rounds, window, workers, batch, ...) on
            # axis 2; rngs last
            *data_rest, rngs = rest

            def body(st, inp):
                st, loss = self._run_round(shmapped, st, inp, rngs)
                return st, loss

            return jax.lax.scan(body, state, (xb, yb) + tuple(data_rest))

        return jax.jit(epoch, donate_argnums=(0,))

    def run_epoch(self, state: DistState, xb, yb, mb, rngs, sb=None
                  ) -> Tuple[DistState, np.ndarray]:
        """xb/yb/mb: np arrays shaped (rounds, window, workers, batch, ...);
        ``mb`` is the per-example real/padding mask from
        ``shape_epoch_data``; ``sb`` (packed engines) the segment ids."""
        self._check_packed(sb)
        if self._epoch_fn is None:
            self._epoch_fn = self._build_epoch_fn()
        sh = NamedSharding(self.mesh, P(None, None, WORKER_AXIS))
        arrays = (xb, yb) + ((sb,) if self.packed else ()) + (mb,)
        arrays = tuple(jax.device_put(a, sh) for a in arrays)
        state, losses = self._epoch_fn(state, *arrays, rngs)
        return state, losses

    def run_round(self, state: DistState, x, y, m, rngs, s=None
                  ) -> Tuple[DistState, jnp.ndarray]:
        """One jitted round from host arrays shaped (window, workers, batch,
        ...) — the round-granular checkpointing path.  Same math as the
        epoch scan (both execute the one shard_map'd round program), at the
        cost of one jit call + device_put per round."""
        self._check_packed(s)
        if self._round_step is None:
            self._round_step = self._build_round_step()
        sh = NamedSharding(self.mesh, P(None, WORKER_AXIS))
        data = (x, y) + ((s,) if self.packed else ()) + (m,)
        return self._round_step(state,
                                *(jax.device_put(a, sh) for a in data),
                                rngs)

    def _check_packed(self, seg):
        if self.packed and seg is None:
            raise ValueError("packed engine needs segment ids")
        if seg is not None and not self.packed:
            raise ValueError("segment ids passed to an unpacked engine — "
                             "construct SPMDEngine(packed=True)")

    # -- streaming epoch (datasets larger than HBM) ---------------------------
    def _build_round_step(self) -> Callable:
        shmapped = self._shmapped_round()

        def step(state: DistState, *args):
            *data, rngs = args
            return self._run_round(shmapped, state, tuple(data), rngs)

        return jax.jit(step, donate_argnums=(0,))

    def run_epoch_streaming(self, state: DistState, round_iter, rngs
                            ) -> Tuple[DistState, np.ndarray]:
        """Run an epoch from a generator of per-round host array tuples —
        (x, y, mask) triples, or (x, y, seg, mask) quadruples on a packed
        engine — shaped (window, workers, batch, ...) (see
        ``data.pipeline.round_stream``; pass ``seg=`` there iff the engine
        is packed), double-buffered onto the mesh.  Same math as
        ``run_epoch`` — one jit call per round instead of one per epoch —
        for datasets that cannot live in HBM whole.
        """
        from ..data.pipeline import prefetch_to_device
        if self._round_step is None:
            self._round_step = self._build_round_step()
        sh = NamedSharding(self.mesh, P(None, WORKER_AXIS))
        # packed engines stream (x, y, seg, mask) quadruples
        # (round_stream(seg=…)); unpacked stream the classic triples.
        # Arity is checked on the RAW iterator, before prefetch's zip could
        # truncate a too-long item (prefetch_to_device also refuses
        # length mismatches as a second line of defense).
        arity = 4 if self.packed else 3

        def checked(it):
            for item in it:
                if len(item) != arity:
                    raise ValueError(
                        f"streamed round has {len(item)} arrays, the "
                        f"{'packed' if self.packed else 'unpacked'} "
                        f"engine expects {arity} — pass seg=… to "
                        "round_stream iff the engine is packed")
                yield item

        losses = []
        for item in prefetch_to_device(checked(round_iter), (sh,) * arity):
            state, loss = self._round_step(state, *item, rngs)
            losses.append(loss)
        # one device→host transfer for the whole epoch, f32 like run_epoch
        return state, np.asarray(jax.device_get(jnp.stack(losses)),
                                 dtype=np.float32)

    def worker_rngs(self, seed: int):
        keys = jax.random.split(jax.random.PRNGKey(seed), self.num_workers)
        return jax.device_put(keys, worker_sharded(self.mesh))


def shape_epoch_data(columns_x: np.ndarray, columns_y: np.ndarray,
                     num_workers: int, window: int, batch_size: int,
                     columns_seg: Optional[np.ndarray] = None):
    """Reshape flat (rows, ...) arrays into (rounds, window, workers, batch,
    ...) plus a per-example mask, padding the tail to a whole round.

    The worker axis is placed *inside* the scan axes so the arrays can be
    device_put with a single ``P(None, None, 'workers')`` sharding and scanned
    over rounds/window without any transposition inside the program.

    SPMD static shapes require an integer number of rounds; instead of
    truncating the tail (which at an 8-worker MNIST config silently dropped
    up to ~18% of each epoch — Spark's repartition drops nothing), the tail
    round is filled by *wrapping* real rows, and the returned mask is 1.0
    for real rows, 0.0 for padding.  Padded examples contribute zero to loss
    and gradients (``make_masked_loss_fn``) while keeping BatchNorm batch
    statistics over real data values.  The layout itself (round-robin deal
    of rows to workers so padding never concentrates on one worker) lives in
    ``data.pipeline.round_block``, shared with the streaming path.

    Returns ``(xb, yb, mask, rounds)``, or ``(xb, yb, sb, mask, rounds)``
    when ``columns_seg`` (sequence-packing segment ids, same row order) is
    given; every real row appears exactly once.
    """
    from ..data.pipeline import num_rounds, round_block
    n, w, b = num_workers, window, batch_size
    rounds = num_rounds(len(columns_x), n, w, b)
    sel = np.empty((rounds, w, n, b), np.int64)
    mask = np.empty((rounds, w, n, b), np.float32)
    for r in range(rounds):
        sel[r], mask[r] = round_block(len(columns_x), n, w, b, r)
    if columns_seg is not None:
        return (columns_x[sel], columns_y[sel], columns_seg[sel], mask,
                rounds)
    return columns_x[sel], columns_y[sel], mask, rounds
