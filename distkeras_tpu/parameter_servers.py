"""Host parameter servers — the semantically-exact asynchronous path.

Reference being replaced: ``distkeras/parameter_servers.py`` (SURVEY.md §2.1
rows 14–16, §3.4): a TCP server thread on the Spark driver holding the center
model; one handler thread per worker connection; 1-byte actions ``'p'``
(pull → send center weights) and ``'c'`` (commit → apply delta).  The
reference applies commits **without a lock** (GIL-tolerated hogwild); we keep
true hogwild *interleaving* across windows but make each individual apply
atomic under a mutex — same algorithm semantics, no torn ndarray writes.

Where this fits in the TPU design: the primary execution engine is the
bulk-synchronous SPMD program over ICI (``parallel/spmd.py``).  This module is
selected with ``Trainer(..., execution='host_ps')`` and exists because true
asynchronous staleness (DOWNPOUR/DynSGD semantics) is *not representable*
inside a single XLA program — so it runs on the host side over DCN/loopback,
with each worker thread driving jitted window steps on its device.  Update
rules mirror the pure functions in ``parallel/rules.py``, applied here as
in-place numpy loops on flat weight lists for commit-path speed;
tests/test_host_ps.py asserts the two implementations agree.

The server core (PR 7) is **event-driven**: one I/O thread multiplexes every
worker connection over a selector (``SocketParameterServer``), and commits
that arrive while an apply is in flight are **coalesced** — applied as one
batch per drain, with runs of sparse commits merged into ONE vectorized
scatter-add (the classic server-side aggregation the PS scaling results
hinge on: Dean et al. NIPS 2012; Li et al. OSDI 2014).  The seed-era
thread-per-connection core is retained as ``ThreadedSocketParameterServer``
(``ps_core="threaded"``) for the before/after worker-scaling bench.
Coalescing semantics per algorithm (docs/host_ps.md):

 - DOWNPOUR / the elastic family: commits within a drain apply in arrival
   order with per-commit arithmetic unchanged, so a coalesced drain is
   BIT-equal to the same commits applied sequentially (sums commute, and
   the accumulation order is preserved per coordinate).
 - ADAG: same — the 1/num_workers scale is clock-independent.
 - DynSGD: staleness is stamped at ENQUEUE (the commit's arrival at the
   server), not at apply: commits coalesced into one drain do not count
   each other as staleness.  Single-worker runs are bit-identical (a
   strict request/reply worker never has two commits in one drain).
"""

from __future__ import annotations

import logging
import os
import selectors
import socket
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import applykernel, networking
from .core.model import FittedModel, deserialize_model, serialize_model
from .ps_sharding import PSShardDown, ShardedServerGroup
from .workers import WORKER_CLASSES, share_compiled_state

logger = logging.getLogger("distkeras_tpu.parameter_servers")


def _flat_offsets(center: List[np.ndarray]):
    """(per-tensor flat offsets, total elements) of the concatenated list."""
    sizes = np.array([int(c.size) for c in center], np.int64)
    offsets = np.concatenate(([0], np.cumsum(sizes)))
    return offsets, int(offsets[-1])


def _validate_sparse(sp: "networking.SparseDelta", total: int,
                     scale: float = 1.0):
    """One sparse commit's (sorted int64 indices, scaled f32 values),
    validated against the dense length — the per-commit normalization of
    ``_scatter_add``, factored out so a coalesced drain can concatenate
    many commits into one scatter-add with unchanged per-commit arithmetic
    (each commit is sorted/scaled exactly as its sequential apply would)."""
    if sp.length != total:
        raise ValueError(
            f"sparse commit declares dense length {sp.length}, center "
            f"has {total} elements")
    idx = sp.indices.astype(np.int64, copy=False)
    vals = sp.f32_values()
    if idx.size:
        if np.any(np.diff(idx) < 0):  # tolerate unsorted senders
            order = np.argsort(idx, kind="stable")
            idx, vals = idx[order], vals[order]
        if idx[0] < 0 or idx[-1] >= total:
            raise ValueError(
                f"sparse commit index out of range for dense length {total}")
    if scale != 1.0:
        vals = vals * np.float32(scale)
    return idx, vals


def _scatter_flat(center: List[np.ndarray], offsets: np.ndarray,
                  idx: np.ndarray, vals: np.ndarray, kernel=None) -> None:
    """One scatter-add of (sorted flat indices, f32 values) over the tensor
    list: the sorted indices are bisected once over the tensor offsets, then
    each tensor gets one sequential scatter-add (``np.add.at`` or the native
    kernel — bit-identical) over its contiguous index run."""
    bounds = np.searchsorted(idx, offsets)
    for t in range(len(center)):
        lo, hi = int(bounds[t]), int(bounds[t + 1])
        if lo == hi:
            continue
        flat = center[t].reshape(-1)  # view: center tensors are contiguous
        applykernel.scatter_add(kernel, flat, idx[lo:hi] - int(offsets[t]),
                                vals[lo:hi])


def _row_scatter_add(tensor: np.ndarray, rsp: "networking.RowSparseDelta",
                     scale: float = 1.0, kernel=None) -> None:
    """Apply a row-sparse delta to ONE tensor: O(k·dim) per-row scatter-add.

    ``rsp`` names touched leading-axis rows of ``tensor``; shapes and row
    range are validated so a hostile or mis-split commit raises instead of
    writing into neighbouring rows.  ``kernel`` routes the per-row axpy
    through the native apply kernel — bit-identical results.
    """
    if tensor.ndim < 2:
        raise ValueError(
            f"row-sparse commit targets a {tensor.ndim}-D tensor; row "
            "sparsity needs a leading row axis")
    if rsp.num_rows != tensor.shape[0]:
        raise ValueError(
            f"row-sparse commit declares {rsp.num_rows} rows, tensor has "
            f"{tensor.shape[0]}")
    if rsp.row_shape != tuple(tensor.shape[1:]):
        raise ValueError(
            f"row-sparse commit rows are shaped {rsp.row_shape}, tensor "
            f"rows are {tuple(tensor.shape[1:])}")
    rows = rsp.rows.astype(np.int64, copy=False)
    if rows.size == 0:
        return
    if int(rows.min()) < 0 or int(rows.max()) >= rsp.num_rows:
        raise ValueError(
            f"row-sparse commit row out of range for {rsp.num_rows} rows")
    vals = np.ascontiguousarray(rsp.f32_values())
    applykernel.row_scatter_add(
        kernel, tensor.reshape(tensor.shape[0], -1), rows,
        vals.reshape(vals.shape[0], -1), scale)


def _scatter_add(center: List[np.ndarray], sp: "networking.SparseDelta",
                 scale: float = 1.0, kernel=None) -> None:
    """Apply a k-sparse flat delta to a tensor list: O(k) scatter-add.

    ``sp`` indexes the concatenation of ``center`` (C-order flat, list
    order); indices are validated against the dense length so a hostile or
    mis-split commit raises instead of corrupting neighbouring tensors.
    The whole apply touches k coordinates, not the n-element center;
    ``kernel`` routes the inner scatter through the native apply kernel
    (``csrc/applykernel.cpp``) when enabled — bit-identical results.
    """
    offsets, total = _flat_offsets(center)
    idx, vals = _validate_sparse(sp, total, scale)
    if idx.size == 0:
        return
    _scatter_flat(center, offsets, idx, vals, kernel)


def _decode_commit_msg(msg):
    """Transport-boundary decompression + wire-contract validation, shared
    by BOTH server cores: int8 codes × per-tensor scales → f32 deltas;
    sparse top-k and row-sparse nodes VALIDATED (sorted unique in-range
    indices — ``networking.ProtocolError`` on violation, which the caller
    treats exactly like a torn frame: drop the connection, center
    untouched) and dequantized/detached to f32 copies, so every PS rule
    sees ordinary floats that outlive the receive buffer."""
    if not isinstance(msg, dict):
        return msg
    if "scales" in msg:
        msg["delta"] = [
            np.asarray(q, np.float32) * s
            for q, s in zip(msg["delta"], msg.pop("scales"))]
        return msg
    delta = msg.get("delta")
    if isinstance(delta, networking.SparseDelta):
        msg["delta"] = delta.validate().decoded()
    elif isinstance(delta, list) and any(
            isinstance(d, networking.RowSparseDelta) for d in delta):
        msg["delta"] = [
            d.validate().decoded()
            if isinstance(d, networking.RowSparseDelta) else d
            for d in delta]
    return msg


class ParameterServer:
    """Base PS (reference: ``parameter_servers.py :: ParameterServer``):
    holds the center weights + the update clock."""

    def __init__(self, model_blob: dict,
                 apply_kernel: Optional[str] = None):
        self.model_blob = model_blob
        self.center: List[np.ndarray] = [
            np.array(w, dtype=np.float32, copy=True)
            for w in model_blob["weights"]]
        self.num_updates = 0
        # the APPLY lock: guards center + clock only.  Connection
        # bookkeeping lives behind SocketParameterServer's own lock, so N
        # workers' commits never serialize behind accept/teardown state.
        self._lock = threading.Lock()
        # apply_kernel= knob (docs/API.md): None/'numpy' keeps the pure-
        # NumPy apply (the default and the bit-equality reference),
        # 'native' requires csrc/applykernel.cpp, 'auto' uses it if built.
        # Resolved eagerly so a bad name / missing build fails loudly at
        # construction, not mid-training under the apply lock.
        self.apply_kernel = apply_kernel
        self._kernel = applykernel.resolve(apply_kernel)

    def initialize(self):
        """Reference-parity hook (center is built in __init__ here)."""

    def next_update(self) -> int:
        self.num_updates += 1
        return self.num_updates

    def get_model(self) -> FittedModel:
        model, params = deserialize_model(
            {"model": self.model_blob["model"], "weights": self.center})
        return FittedModel(model, params)

    # -- the per-algorithm apply rule (subclasses override _scale) -----------
    def _scale(self, msg: Dict[str, Any]) -> float:
        """The scalar every rule reduces one commit to (called with
        ``_lock`` HELD).  This reduction is what lets sparsity AND drain
        coalescing compose with all the rules: a drain pre-computes each
        commit's scale, then applies the batch with per-commit arithmetic
        unchanged."""
        raise NotImplementedError

    def _apply(self, msg: Dict[str, Any]):
        """Apply one commit to the center.  Called with ``_lock`` HELD."""
        self._apply_scaled(msg, self._scale(msg))

    def _apply_scaled(self, msg: Dict[str, Any], scale: float):
        """Shared commit arithmetic: ``center += scale * delta`` for a dense
        tensor list, or an O(k) scatter-add for a k-sparse commit
        (``networking.SparseDelta`` — the ``wire_dtype="topk"`` wire form).
        Every rule reduces to a scalar ``scale``, so sparsity composes with
        all of them under the same apply lock.  With ``apply_kernel`` the
        dense axpy and the sparse scatter run through the native kernel —
        bit-identical to the numpy path (tests/test_applykernel.py)."""
        delta = msg["delta"]
        if isinstance(delta, networking.SparseDelta):
            _scatter_add(self.center, delta, scale, self._kernel)
        else:
            # a delta LIST may mix dense tensors with row-sparse embedding
            # blocks (``row_sparse=`` commits): dense entries apply as one
            # axpy each, row-sparse entries as an O(k·dim) row scatter-add
            # — same scalar ``scale``, so every rule composes unchanged
            for c, d in zip(self.center, delta):
                if isinstance(d, networking.RowSparseDelta):
                    _row_scatter_add(c, d, scale, self._kernel)
                else:
                    applykernel.axpy(
                        self._kernel, c.reshape(-1),
                        np.asarray(d).astype(np.float32,
                                             copy=False).reshape(-1),
                        scale)
        self.next_update()

    # -- coalesced drains (the event-driven core's batch apply) --------------
    def apply_drain(self, msgs: List[Dict[str, Any]]) -> int:
        """Apply transport-decoded commit messages in ARRIVAL ORDER under
        ONE lock acquisition, merging runs of consecutive sparse commits
        into one vectorized scatter-add.  Returns the clock after the
        drain.  Semantics per algorithm (module docstring + docs/host_ps.md):
        DOWNPOUR/ADAG coalesced results are bit-equal to the same commits
        applied sequentially; DynSGD prices staleness from each commit's
        ``_arrival`` stamp (set at enqueue by the event server) instead of
        the mid-drain clock."""
        with self._lock:
            self._apply_drain_locked(msgs)
            return self.num_updates

    def _apply_drain_locked(self, msgs: List[Dict[str, Any]]):
        i, n = 0, len(msgs)
        while i < n:
            if isinstance(msgs[i].get("delta"), networking.SparseDelta):
                j = i + 1
                while j < n and isinstance(msgs[j].get("delta"),
                                           networking.SparseDelta):
                    j += 1
                self._apply_sparse_run_locked(msgs[i:j])
                i = j
            else:
                # dense commits apply in arrival order with per-commit
                # arithmetic (one axpy per tensor) — pre-summing deltas
                # would re-round the accumulation and break the DOWNPOUR
                # bit-equality contract; the coalescing win here is one
                # lock acquisition and ONE reply snapshot per drain
                self._apply(msgs[i])
                i += 1

    def _apply_sparse_run_locked(self, msgs: List[Dict[str, Any]]):
        """A run of consecutive sparse commits as ONE scatter-add: each
        commit is sorted/scaled exactly as its sequential apply would be,
        the segments are concatenated, and a STABLE argsort merges them —
        stability keeps every coordinate's additions in arrival order, so
        the float accumulation (and hence the result) is bit-identical to
        applying the commits one by one."""
        if len(msgs) == 1:
            self._apply(msgs[0])
            return
        offsets, total = _flat_offsets(self.center)
        parts_i, parts_v = [], []
        for m in msgs:
            # scale BEFORE bumping the clock for this commit — the exact
            # sequence of the sequential path (DynSGD's fallback baseline
            # reads num_updates when no _arrival stamp is present)
            idx, vals = _validate_sparse(m["delta"], total, self._scale(m))
            parts_i.append(idx)
            parts_v.append(vals)
            self.next_update()
        idx = np.concatenate(parts_i)
        vals = np.concatenate(parts_v)
        if idx.size == 0:
            return
        order = np.argsort(idx, kind="stable")
        _scatter_flat(self.center, offsets, idx[order], vals[order],
                      self._kernel)

    def handle_commit(self, msg: Dict[str, Any]):
        with self._lock:
            self._apply(msg)

    def handle_update(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """``'u'`` = commit+pull: apply the delta and snapshot center+clock
        under ONE lock acquisition, so the reply is exactly the center this
        commit produced (plus any commits that landed before it) — the
        atomic combined round trip the overlapped workers ride."""
        with self._lock:
            self._apply(msg)
            return {"weights": [w.copy() for w in self.center],
                    "clock": self.num_updates}

    def handle_pull(self) -> Dict[str, Any]:
        with self._lock:
            return {"weights": [w.copy() for w in self.center],
                    "clock": self.num_updates}

    def handle_heartbeat(self) -> Dict[str, Any]:
        """``'h'``: cheap liveness probe — clock only, no weights.  Goes
        through the apply lock *deliberately*: a shard wedged inside an
        apply must fail the heartbeat deadline, not answer "alive" while
        every commit stalls (resilience.ShardSupervisor)."""
        with self._lock:
            return {"clock": self.num_updates}


class DeltaParameterServer(ParameterServer):
    """center += delta (reference: ``DeltaParameterServer`` — DOWNPOUR's and
    the elastic family's PS; for EASGD the committed 'delta' is the elastic
    term, so the same rule applies)."""

    def _scale(self, msg):
        return 1.0


class ADAGParameterServer(ParameterServer):
    """ADAG normalization (reference: ``ADAGParameterServer``): accumulated
    deltas are normalized over the number of concurrent committers before
    applying — the per-commit form of ``rules.adag_commit`` (which divides
    the cross-worker sum by the worker count)."""

    def __init__(self, model_blob, num_workers: int,
                 apply_kernel: Optional[str] = None):
        super().__init__(model_blob, apply_kernel=apply_kernel)
        self.num_workers = max(int(num_workers), 1)

    def _scale(self, msg):
        return 1.0 / self.num_workers


class DynSGDParameterServer(ParameterServer):
    """Staleness-aware apply (reference: ``DynSGDParameterServer``):
    center += delta / (staleness + 1), where staleness = updates that landed
    since this worker's last pull (the commit's ``clock`` field) — exactly
    ``rules.dynsgd_commit``.

    Coalescing ordering rule (docs/host_ps.md): the staleness baseline is
    the ``_arrival`` stamp the event server sets when the commit is
    ENQUEUED, so commits coalesced into one drain do not count each other
    as staleness — the drain prices every member against the clock it
    actually arrived at.  Without a stamp (direct calls, the threaded
    core) the baseline falls back to the live clock: the exact sequential
    semantics of the seed-era server, bit for bit."""

    def _scale(self, msg):
        baseline = int(msg.get("_arrival", self.num_updates))
        staleness = max(baseline - int(msg.get("clock", 0)), 0)
        return 1.0 / (staleness + 1.0)


def _enable_keepalive(sock: socket.socket,
                      idle_deadline: Optional[float] = None) -> None:
    """Kernel-level dead-peer detection on an accepted PS connection: a
    host that vanished without a FIN (power loss, hard partition) stops
    acking keepalive probes and the kernel errors the socket out of its
    blocked recv — the transport-level half of half-open reaping (the
    application-level half is ``idle_deadline``).  With a deadline set,
    the probe schedule is tightened to fire WITHIN it (idle at half the
    deadline, then up to 3 probes); without one, the OS defaults (hours)
    apply.  Every knob is best-effort — platforms without TCP_KEEPIDLE
    simply keep the plain SO_KEEPALIVE bit."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    except OSError:
        return
    if idle_deadline is None:
        return
    idle = max(1, int(idle_deadline / 2))
    intvl = max(1, int(idle_deadline / 6))
    for opt, val in (("TCP_KEEPIDLE", idle), ("TCP_KEEPINTVL", intvl),
                     ("TCP_KEEPCNT", 3)):
        if hasattr(socket, opt):
            try:
                sock.setsockopt(socket.IPPROTO_TCP,
                                getattr(socket, opt), val)
            except OSError:
                pass


class ThreadedSocketParameterServer:
    """The seed-era thread-per-connection PS core (reference:
    ``SocketParameterServer.run`` — thread per connection, opcode dispatch).

    Retained behind ``ps_core="threaded"`` as the before/after baseline for
    the ``host_ps_worker_scaling`` bench: one handler thread per worker
    connection, one apply-lock acquisition and one full center snapshot per
    commit.  Structurally wrong at large worker counts — N threads churn
    the GIL and every 'u' pays an O(n) copy — which is exactly what the
    event-driven ``SocketParameterServer`` replaces.

    Composition instead of inheritance so the apply rules above stay pure-ish
    and unit-testable without sockets.
    """

    def __init__(self, ps: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0, generation: int = 0,
                 idle_deadline: Optional[float] = None):
        self.ps = ps
        self.host = host
        self.port = port  # 0 → ephemeral; real port set by start()
        # recovery epoch (resilience.ShardSupervisor): bumped on every
        # respawn of this address.  Replies carry it; commits stamped with
        # an older generation are rejected (they were computed against a
        # center this restart rolled back) — the epoch/generation handshake.
        self.generation = int(generation)
        # half-open reaping (docs/host_ps.md failure matrix): a WAN peer
        # that vanished without a FIN (partition, SIGKILLed host, NAT state
        # loss) leaves its handler blocked in recv forever.  idle_deadline
        # seconds of silence reaps the connection — the worker re-dials and
        # resumes under its RetryPolicy, so reaping costs one reconnect,
        # never a lost commit.  None (default) keeps the seed behavior:
        # only kernel keepalive (always on) eventually notices.
        self.idle_deadline = (None if idle_deadline is None
                              else float(idle_deadline))
        if self.idle_deadline is not None and self.idle_deadline <= 0:
            raise ValueError("idle_deadline must be > 0 (or None)")
        #: connections reaped for idle_deadline silence (observability)
        self.reaped = 0
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_of: Dict[threading.Thread, socket.socket] = {}
        self._conn_lock = threading.Lock()  # guards: _conns, _conn_threads, _conn_of, _running
        self._running = False

    # -- lifecycle (reference: initialize/start/stop) ------------------------
    def start(self):
        self.ps.initialize()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(128)
        with self._conn_lock:
            self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dkt-ps-accept")
        self._accept_thread.start()

    def stop(self, join_timeout: float = 5.0):
        """Idempotent shutdown that actually unblocks every thread.

        Closing an fd from another thread does not reliably interrupt a
        blocked ``accept()`` on Linux, so we wake the accept loop with a
        self-connection, join it, then ``shutdown(SHUT_RDWR)`` every accepted
        connection to kick handler threads out of ``recv`` before joining
        them.  A handler that outlives its ``join_timeout`` (wedged inside
        an apply, not a recv) is no longer leaked silently: the leak is
        logged and its connection socket force-closed again, so a thread
        stuck in socket I/O unblocks and one stuck in compute at least
        fails fast on its next send instead of writing to a live peer.
        """
        with self._conn_lock:
            was_running = self._running
            self._running = False
        if was_running and self._server is not None:
            try:  # wake the blocked accept(); loop sees _running=False
                wake = socket.create_connection((self.host, self.port),
                                                timeout=1.0)
                wake.close()
            except OSError:
                pass  # server socket already dead — accept has returned
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._conn_lock:
            conns, threads = list(self._conns), list(self._conn_threads)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=join_timeout)
            if t.is_alive():
                logger.warning(
                    "PS handler thread %s still alive after stop(join_"
                    "timeout=%.1fs) — likely wedged in an apply; force-"
                    "closing its connection and leaving it to die detached",
                    t.name, join_timeout)
                with self._conn_lock:
                    conn = self._conn_of.get(t)
                if conn is not None:
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass

    @property
    def live_connections(self) -> int:
        """Connections with a live handler thread — the bookkeeping a
        half-frame worker death must decrement (a dying worker's torn
        commit drops its connection silently: no codec error escapes the
        handler, no `_conns` entry leaks; tests/test_elastic_workers.py)."""
        with self._conn_lock:
            return len(self._conns)

    def crash(self):
        """Abrupt-death simulation (chaos/bench hook): close the listener
        and every connection with no graceful shutdown, no joins, no final
        state flush — the in-process analogue of a SIGKILLed shard.  The
        in-memory center is deliberately abandoned; recovery must come from
        the last journal snapshot (resilience.ShardSupervisor), which is
        exactly the bounded-loss contract under test."""
        with self._conn_lock:
            self._running = False
            conns = list(self._conns)
        if self._server is not None:
            # shutdown() interrupts a blocked accept() (close() alone does
            # not on Linux — the accept syscall pins the open file
            # description, which would keep the PORT bound and block a
            # same-address respawn with EADDRINUSE)
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        for c in conns:
            networking._hard_close(c)

    def get_model(self) -> FittedModel:
        return self.ps.get_model()

    def respawn_clone(self, ps: ParameterServer
                      ) -> "ThreadedSocketParameterServer":
        """A same-core replacement server on this address with the
        generation bumped (resilience.ShardSupervisor.respawn_shard)."""
        return ThreadedSocketParameterServer(
            ps, host=self.host, port=self.port,
            generation=self.generation + 1,
            idle_deadline=self.idle_deadline)

    # -- service loops -------------------------------------------------------
    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # socket closed by stop()
            with self._conn_lock:
                if not self._running:  # stop()'s wake connection, or late join
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                _enable_keepalive(conn, self.idle_deadline)
                if self.idle_deadline is not None:
                    # blocked recv/send wakes with socket.timeout after
                    # this much silence → the handler reaps the half-open
                    # connection instead of pinning a thread forever
                    conn.settimeout(self.idle_deadline)
                t = threading.Thread(
                    target=self._handle_connection, args=(conn,),
                    daemon=True, name="dkt-ps-conn")
                self._conns.append(conn)
                self._conn_threads.append(t)
                self._conn_of[t] = conn
            t.start()

    def _handle_connection(self, conn: socket.socket):
        """Reference: ``handle_connection`` — loop on 1-byte actions until
        EOF/quit ('p' pull, 'c' commit, 'u' commit+pull, 'h' heartbeat,
        'q' quit).  Every reply carries this server's ``generation``."""
        # per-connection send pool: replies (full center, fixed layout)
        # re-serialize into the same preallocated buffer every round trip
        # instead of allocating a weight-sized output blob per reply
        send_pool = networking.BufferPool()
        try:
            while True:
                op = networking.recv_opcode(conn)
                if op in (b"", b"q"):
                    return
                if op == b"p":
                    reply = self.ps.handle_pull()
                    reply["gen"] = self.generation
                    networking.send_data(conn, reply, pool=send_pool)
                elif op == b"h":
                    # liveness probe (resilience.ShardSupervisor): clock +
                    # generation, no weights — and it takes the apply lock,
                    # so a wedged apply fails the probe deadline
                    reply = self.ps.handle_heartbeat()
                    reply["gen"] = self.generation
                    networking.send_data(conn, reply, pool=send_pool)
                elif op in (b"c", b"u"):
                    try:
                        # decode + the shared transport-boundary pass
                        # (_decode_commit_msg): int8 dequantization, sparse
                        # top-k / row-sparse validation (ProtocolError ⊂
                        # ValueError) — a contract-violating commit drops
                        # the connection exactly like a torn frame, before
                        # any apply could corrupt the center
                        msg = _decode_commit_msg(
                            networking.recv_data(conn))
                    except ValueError:
                        return  # torn/corrupt/hostile frame: drop it
                    # generation handshake: a commit stamped with an older
                    # generation was computed against a center a restart
                    # rolled back — drop it (bounded loss, same class as
                    # worker staleness) instead of applying it to the
                    # restored center.  'u' still replies with the current
                    # state + generation so the worker re-syncs in the same
                    # round trip.
                    gen = msg.get("gen") if isinstance(msg, dict) else None
                    stale = gen is not None and int(gen) != self.generation
                    # apply-rule errors deliberately propagate (visible
                    # thread traceback) — only transport faults are silent
                    if op == b"c":
                        if not stale:
                            self.ps.handle_commit(msg)
                    else:
                        # 'u': apply + snapshot atomically, reply in the
                        # same round trip (one DCN RTT per window instead
                        # of a commit send followed by a pull round trip)
                        if stale:
                            reply = self.ps.handle_pull()
                            reply["stale"] = True
                        else:
                            reply = self.ps.handle_update(msg)
                        reply["gen"] = self.generation
                        networking.send_data(conn, reply, pool=send_pool)
                else:
                    return  # protocol violation: drop the connection
        except socket.timeout:
            # idle_deadline of silence: the peer is half-open (vanished
            # without FIN) or wedged — reap the connection; a live worker
            # re-dials under its RetryPolicy
            self.reaped += 1
            return
        except (ConnectionError, OSError):
            # worker died: reference behavior is silent handler exit; the
            # server keeps serving the others
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            me = threading.current_thread()
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                if me in self._conn_threads:
                    self._conn_threads.remove(me)
                self._conn_of.pop(me, None)


#: event-loop receive chunk: big enough that a steady-state commit frame
#: lands complete in ONE recv (the parser's zero-copy fast path); frames
#: larger than this reassemble through the parser accumulator (correct,
#: just pays copies — docs/TUNING.md)
_RECV_CHUNK = 1 << 20


class _EventConn:
    """Per-connection state on the event loop: a pooled receive scratch
    (``recv_into`` lands every chunk in the same reused memory — no
    per-recv allocation), the incremental frame parser decoding zero-copy
    views over that scratch, and the pending-write queue with its encode
    pool (replies re-serialize into reusable pooled memory).

    Lifetime contract for the decoded views: the loop drains every parsed
    request at the end of the SAME iteration that read it, and the next
    ``recv_into`` on this connection can only happen in a later iteration
    — so the scratch is never overwritten under a live commit.  This is
    the pooled-``recv_data`` contract, per connection."""

    __slots__ = ("sock", "parser", "out", "recv_pool", "send_pool",
                 "want_write", "last_activity")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.parser = networking.FrameParser()
        self.out: List[memoryview] = []
        self.recv_pool = networking.BufferPool()
        self.send_pool = networking.BufferPool()
        self.want_write = False
        #: monotonic instant of the last byte received (half-open reaping:
        #: idle_deadline of silence → the loop drops this connection)
        self.last_activity = time.monotonic()


class SocketParameterServer:
    """The event-driven PS core: ONE I/O thread multiplexes every worker
    connection over a selector (the ``ChaosProxy``-style frame relay idiom,
    turned into the server), with per-connection read/write buffers and
    commit COALESCING.

    Protocol, reply shapes, generation handshake, heartbeat semantics, and
    torn-frame policy are identical to ``ThreadedSocketParameterServer`` —
    the full resilience/elastic/chaos test matrix runs unchanged on this
    core.  What changes is the execution shape:

     - **No thread per connection.**  Accepting, reading, parsing, applying,
       and replying all happen on one thread driving a ``selectors``
       event loop; hundreds of workers cost hundreds of registered fds,
       not hundreds of Python threads fighting the GIL.
     - **Coalesced applies.**  Commits that arrive while an apply is in
       flight accumulate in the kernel's socket buffers; the next loop
       iteration parses them all and applies them as ONE drain — one apply-
       lock acquisition, runs of sparse commits merged into one vectorized
       scatter-add (``ParameterServer.apply_drain``), and the post-drain
       center serialized ONCE with every 'u' reply in the drain sharing
       the same encoded frame (the seed core paid an O(n) snapshot copy
       plus an O(n) encode per commit).  Ordering: commits apply in arrival
       order; DOWNPOUR/ADAG drains are bit-equal to sequential applies,
       DynSGD stamps staleness at enqueue (class docstrings +
       docs/host_ps.md).  ``coalesce=False`` degrades every drain to
       batches of one with a per-commit snapshot — the sequential
       semantics, still on the event loop.
     - **Heartbeats still probe the apply.**  'h' is answered by the same
       thread that applies, after everything queued before it — a server
       wedged inside an apply answers no probe, exactly the property
       ``resilience.ShardSupervisor`` detects wedges by.

    An apply-rule error (hostile shapes, mis-split sparse commit) is logged
    with its traceback and costs the offending drain's connections — the
    loop itself survives, where the threaded core sacrificed one handler
    thread.  ``_conn_threads`` is kept as an (always empty) attribute for
    callers that assert the seed core's per-connection threads unwound.
    """

    def __init__(self, ps: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0, generation: int = 0, coalesce: bool = True,
                 idle_deadline: Optional[float] = None):
        self.ps = ps
        self.host = host
        self.port = port  # 0 → ephemeral; real port set by start()
        # recovery epoch (resilience.ShardSupervisor): bumped on every
        # respawn of this address; replies carry it, older-generation
        # commits are rejected (the epoch/generation handshake)
        self.generation = int(generation)
        self.coalesce = bool(coalesce)
        # half-open reaping (docs/host_ps.md failure matrix): a peer gone
        # without a FIN holds its fd registered forever.  idle_deadline
        # seconds without a received byte reaps the registration (the
        # worker re-dials under its RetryPolicy); None keeps reaping off
        # and only kernel keepalive (always on) eventually notices.
        self.idle_deadline = (None if idle_deadline is None
                              else float(idle_deadline))
        if self.idle_deadline is not None and self.idle_deadline <= 0:
            raise ValueError("idle_deadline must be > 0 (or None)")
        #: connections reaped for idle_deadline silence (observability)
        self.reaped = 0
        self._server: Optional[socket.socket] = None
        self._selector: Optional[selectors.BaseSelector] = None
        self._waker: Optional[tuple] = None  # (recv side, send side)
        #: the I/O thread.  The name is load-bearing: the shard
        #: supervisor's liveness check reads ``_accept_thread.is_alive()``
        #: on either core.
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: Dict[socket.socket, _EventConn] = {}
        self._conn_lock = threading.Lock()  # guards: _conns, _running
        self._conn_threads: List[threading.Thread] = []  # event core: none
        # server-level pool for the drain's SHARED 'u' reply frame (every
        # connection in a drain queues a view of the same encoded bytes)
        self._reply_pool = networking.BufferPool()
        self._running = False
        #: coalescing observability (bench host_ps_worker_scaling + tests):
        #: drains = commit batches applied, commits_applied = commits in
        #: them, coalesced_drains = drains that merged >= 2, max_drain =
        #: largest batch
        self.drains = 0
        self.commits_applied = 0
        self.coalesced_drains = 0
        self.max_drain = 0

    @property
    def coalesce_stats(self) -> Dict[str, Any]:
        return {"drains": self.drains,
                "commits_applied": self.commits_applied,
                "coalesced_drains": self.coalesced_drains,
                "max_drain": self.max_drain,
                "mean_drain": (round(self.commits_applied
                                     / self.drains, 3)
                               if self.drains else None)}

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self.ps.initialize()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(128)
        self._server.setblocking(False)
        # the waker: a socketpair registered in the selector.  stop()/
        # crash() write one byte to interrupt a blocked select() — no
        # self-connection through the public listener required.
        r, w = socket.socketpair()
        r.setblocking(False)
        self._waker = (r, w)
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._server, selectors.EVENT_READ, None)
        self._selector.register(r, selectors.EVENT_READ, None)
        with self._conn_lock:
            self._running = True
        self._accept_thread = threading.Thread(
            target=self._io_loop, daemon=True, name="dkt-ps-io")
        self._accept_thread.start()

    def _wake(self):
        if self._waker is not None:
            try:
                self._waker[1].send(b"\0")
            except OSError:
                pass

    def stop(self, join_timeout: float = 5.0):
        """Idempotent shutdown, entirely through the event loop.

        The seed core had to wake its blocked ``accept()`` with a
        self-connection to its own port (closing an fd from another thread
        does not reliably interrupt ``accept`` on Linux); the event core
        needs no such hack — the loop blocks in ``select()`` over a
        socketpair waker, so stop() writes one byte, the loop wakes,
        drains the selector, flushes every connection's pending write
        buffer (bounded best-effort), and closes every registered
        connection plus the listener itself.

        A loop that outlives ``join_timeout`` is wedged inside an apply
        (not I/O — the loop never blocks on a socket).  The leak is logged
        and every connection plus the listener is force-closed from here,
        so the wedged thread fails fast on its next socket op and a
        same-address respawn is not blocked by the old listener.
        """
        with self._conn_lock:
            was_running = self._running
            self._running = False
        self._wake()
        t = self._accept_thread
        if t is not None:
            t.join(timeout=join_timeout)
            if t.is_alive():
                logger.warning(
                    "PS I/O thread %s still alive after stop(join_timeout="
                    "%.1fs) — likely wedged in an apply; force-closing its "
                    "connections and listener and leaving it to die "
                    "detached", t.name, join_timeout)
                with self._conn_lock:
                    conns = list(self._conns.values())
                    self._conns.clear()
                for conn in conns:
                    try:
                        conn.sock.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.sock.close()
                    except OSError:
                        pass
        # belt and braces: the loop's own shutdown closes these; after a
        # crash()/wedge they may still be open
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if was_running is False and t is not None and not t.is_alive():
            self._close_waker()

    def _close_waker(self):
        if self._waker is not None:
            for s in self._waker:
                try:
                    s.close()
                except OSError:
                    pass
            self._waker = None

    @property
    def live_connections(self) -> int:
        """Registered worker connections — the bookkeeping a half-frame
        worker death must decrement (a dying worker's torn commit drops
        its connection silently: no codec error escapes the loop, no
        registration leaks; tests/test_elastic_workers.py)."""
        with self._conn_lock:
            return len(self._conns)

    def crash(self):
        """Abrupt-death simulation (chaos/bench hook): close the listener
        and every connection with no graceful shutdown, no flush, no final
        state — the in-process analogue of a SIGKILLed shard.  The
        in-memory center is deliberately abandoned; recovery must come
        from the last journal snapshot (resilience.ShardSupervisor), the
        bounded-loss contract under test.  The port is released
        immediately so a same-address respawn can bind."""
        with self._conn_lock:
            self._running = False
            conns = list(self._conns.values())
            self._conns.clear()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        for conn in conns:
            networking._hard_close(conn.sock)
        self._wake()

    def get_model(self) -> FittedModel:
        return self.ps.get_model()

    def respawn_clone(self, ps: ParameterServer) -> "SocketParameterServer":
        """A same-core replacement server on this address with the
        generation bumped and the coalescing knob carried over
        (resilience.ShardSupervisor.respawn_shard)."""
        return SocketParameterServer(ps, host=self.host, port=self.port,
                                     generation=self.generation + 1,
                                     coalesce=self.coalesce,
                                     idle_deadline=self.idle_deadline)

    # -- the event loop ------------------------------------------------------
    def _io_loop(self):
        sel = self._selector
        entries: List[tuple] = []
        # with reaping on, the loop must wake even when every peer is
        # silent — bound the select timeout well inside the deadline
        timeout = (None if self.idle_deadline is None
                   else min(max(self.idle_deadline / 4.0, 0.05), 1.0))
        try:
            while True:
                with self._conn_lock:
                    if not self._running:
                        return
                try:
                    events = sel.select(timeout=timeout)
                except OSError:
                    # fds hard-closed under us (crash()); re-check and exit
                    continue
                if self.idle_deadline is not None:
                    self._reap_idle()
                del entries[:]
                for key, mask in events:
                    if key.fileobj is self._server:
                        self._accept_ready()
                    elif (self._waker is not None
                          and key.fileobj is self._waker[0]):
                        try:
                            self._waker[0].recv(4096)
                        except OSError:
                            pass
                    else:
                        conn = key.data
                        if conn is None:
                            continue
                        if mask & selectors.EVENT_WRITE:
                            self._flush(conn)
                        if mask & selectors.EVENT_READ:
                            self._read_ready(conn, entries)
                if entries:
                    self._process_drain(entries)
        finally:
            self._shutdown_io()

    def _accept_ready(self):
        while True:
            try:
                sock, _ = self._server.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            with self._conn_lock:
                if not self._running:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                try:
                    sock.setblocking(False)
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    _enable_keepalive(sock, self.idle_deadline)
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
                conn = _EventConn(sock)
                self._conns[sock] = conn
            try:
                self._selector.register(sock, selectors.EVENT_READ, conn)
            except (KeyError, ValueError, OSError):
                self._drop(conn)

    def _reap_idle(self):
        """Drop every registered connection silent past ``idle_deadline``
        — the event-core half-open reap (the per-connection stamp is the
        last received byte; writes don't count, a peer owing us nothing
        but reading replies still acks into our recv path via the probe
        traffic its client layer sends)."""
        cutoff = time.monotonic() - self.idle_deadline
        with self._conn_lock:
            stale = [c for c in self._conns.values()
                     if c.last_activity < cutoff]
        for conn in stale:
            self.reaped += 1
            logger.info("reaping half-open PS connection (silent > %.1fs)",
                        self.idle_deadline)
            self._drop(conn)

    def _drop(self, conn: _EventConn):
        """Silent connection teardown (EOF, torn frame, protocol
        violation, send fault) — the reference policy: the server keeps
        serving the others, bookkeeping decrements."""
        with self._conn_lock:
            self._conns.pop(conn.sock, None)
        try:
            self._selector.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        del conn.out[:]

    def _read_ready(self, conn: _EventConn, entries: List[tuple]):
        while True:
            # direct-fill continuation first: a frame torn across recvs
            # streams straight into the parser's preallocated frame buffer
            # (no chunk copy); otherwise land the bytes in the pooled
            # scratch and let the parser decode zero-copy views over it
            target = conn.parser.writable()
            fed_scratch = target is None
            if fed_scratch:
                target = memoryview(conn.recv_pool.get(_RECV_CHUNK))
            try:
                n = conn.sock.recv_into(target)
            except (BlockingIOError, InterruptedError):
                return
            except (ConnectionError, OSError):
                self._drop(conn)
                return
            if not n:
                self._drop(conn)  # EOF; a partial frame dropped silently
                return
            conn.last_activity = time.monotonic()
            if fed_scratch:
                conn.parser.feed(target[:n])
            else:
                conn.parser.advance(n)
            got = False
            try:
                for op, msg in conn.parser.messages():
                    got = True
                    if op in (b"c", b"u"):
                        msg = self._decode_commit(msg)
                        gen = (msg.get("gen") if isinstance(msg, dict)
                               else None)
                        stale = (gen is not None
                                 and int(gen) != self.generation)
                        if stale and op == b"c":
                            continue  # dropped: bounded loss, no reply owed
                        if not stale and isinstance(msg, dict):
                            # the DynSGD ordering rule: staleness is priced
                            # against the clock at ENQUEUE, so commits
                            # coalesced into one drain don't count each
                            # other
                            msg["_arrival"] = self.ps.num_updates
                        entries.append((conn, op, msg, stale))
                    elif op in (b"p", b"h"):
                        entries.append((conn, op, None, False))
                    else:  # b"q" quit, or protocol violation: drop either
                        self._drop(conn)
                        return
            except ValueError:
                self._drop(conn)  # torn/corrupt frame: drop the connection
                return
            if got:
                # parsed requests may be zero-copy views into this round's
                # scratch — stop before the next recv can overwrite them
                # (the drain at this iteration's end consumes them; a
                # level-triggered selector re-arms for what's left)
                return

    @staticmethod
    def _decode_commit(msg):
        """Transport-boundary decompression + validation, identical to the
        threaded core (``_decode_commit_msg``): int8 dequantization, sparse
        top-k / row-sparse index validation — a ``ProtocolError`` propagates
        as ``ValueError`` to ``_read_ready``'s handler, which drops the
        connection exactly as on a torn frame."""
        return _decode_commit_msg(msg)

    # -- drain processing ----------------------------------------------------
    def _process_drain(self, entries: List[tuple]):
        """One event-loop iteration's parsed requests, in arrival order.
        Maximal runs of commits become coalesced apply batches; pulls and
        heartbeats between them snapshot at their own arrival point."""
        replies: List[tuple] = []
        i, n = 0, len(entries)
        while i < n:
            conn, op, msg, stale = entries[i]
            if op in (b"c", b"u"):
                j = i
                batch = []
                while j < n and entries[j][1] in (b"c", b"u"):
                    batch.append(entries[j])
                    j += 1
                if self.coalesce:
                    self._apply_batch(batch, replies)
                else:
                    for e in batch:  # sequential semantics, per-commit
                        self._apply_batch([e], replies)
                i = j
            elif op == b"p":
                reply = self.ps.handle_pull()
                reply["gen"] = self.generation
                replies.append((conn, reply))
                i += 1
            else:  # b"h": through the apply path, as the threaded core's
                # heartbeat went through the apply lock — a wedged apply
                # blocks this loop and the probe times out
                reply = self.ps.handle_heartbeat()
                reply["gen"] = self.generation
                replies.append((conn, reply))
                i += 1
        for conn, obj in replies:
            self._queue_reply(conn, obj)

    def _apply_batch(self, batch: List[tuple], replies: List[tuple]):
        """Apply one commit batch under ONE lock acquisition and serialize
        the center ONCE for every 'u' reply in it.  The shared post-drain
        center is each commit's own result plus any commits that landed in
        the same drain — a strictly fresher center of the same bounded-
        staleness class the async rules already tolerate (docs/host_ps.md).

        The reply is encoded straight from the live center *under the
        apply lock* — the encoded frame IS the snapshot, so a drain pays
        one O(n) serialization total where the threaded core pays a
        snapshot copy plus an encode per commit.  The shared bytes are
        immutable; every involved connection queues a view of the same
        frame."""
        live = [e[2] for e in batch if not e[3]]
        pulls = [e for e in batch if e[1] == b"u"]
        encoded = encoded_stale = None
        try:
            with self.ps._lock:
                if live:
                    self.ps._apply_drain_locked(live)
                if pulls:
                    reply = {"weights": self.ps.center,
                             "clock": self.ps.num_updates,
                             "gen": self.generation}
                    if any(not e[3] for e in pulls):
                        encoded = self._encode_shared(reply)
                    if any(e[3] for e in pulls):
                        reply["stale"] = True
                        encoded_stale = networking.encode_message(reply)
        except Exception:
            # a hostile/mis-split commit must not kill the loop (the
            # threaded core sacrificed one handler thread; here the
            # offending drain's connections pay instead)
            logger.exception(
                "PS apply failed for a drain of %d commits; dropping the "
                "%d involved connections", len(live),
                len({id(e[0]) for e in batch}))
            for e in batch:
                self._drop(e[0])
            return
        if live:
            self.drains += 1
            self.commits_applied += len(live)
            if len(live) >= 2:
                self.coalesced_drains += 1
            self.max_drain = max(self.max_drain, len(live))
        for conn, op, msg, stale in pulls:
            replies.append((conn, encoded_stale if stale else encoded))

    def _encode_shared(self, reply) -> memoryview:
        """Serialize the drain's shared 'u' reply, into the server-level
        pooled buffer when it is provably free — i.e. no connection holds
        a pending (possibly pooled) write — else into fresh bytes.  In
        steady state replies flush synchronously (loopback/LAN socket
        buffers dwarf a frame), so every drain reuses the same memory; a
        backpressured connection downgrades the next drains to fresh
        allocations until it flushes."""
        with self._conn_lock:
            pool_free = all(not c.out for c in self._conns.values())
        if pool_free:
            return networking.encode_message_into(reply, self._reply_pool)
        return memoryview(networking.encode_message(reply))

    # -- the write path ------------------------------------------------------
    def _queue_reply(self, conn: _EventConn, obj):
        """Queue one reply.  ``obj`` is either a message dict ('p'/'h'
        replies, encoded into this connection's pooled send buffer) or the
        drain's pre-encoded shared 'u' frame (immutable bytes — many
        connections may hold views of the same frame)."""
        with self._conn_lock:
            if conn.sock not in self._conns:
                return  # dropped while its reply was being built
        if isinstance(obj, (bytes, memoryview)):
            data = memoryview(obj)
        elif conn.out:
            # the pooled buffer still backs an in-flight reply (a client
            # pipelining past the request/reply contract): fresh bytes
            data = memoryview(networking.encode_message(obj))
        else:
            data = memoryview(networking.encode_message_into(
                obj, conn.send_pool))
        conn.out.append(data)
        self._flush(conn)

    def _flush(self, conn: _EventConn):
        while conn.out:
            buf = conn.out[0]
            try:
                sent = conn.sock.send(buf)
            except (BlockingIOError, InterruptedError):
                break
            except (ConnectionError, OSError):
                self._drop(conn)
                return
            if sent < len(buf):
                conn.out[0] = buf[sent:]
                break
            conn.out.pop(0)
        want = bool(conn.out)
        if want != conn.want_write:
            conn.want_write = want
            mask = selectors.EVENT_READ | (
                selectors.EVENT_WRITE if want else 0)
            try:
                self._selector.modify(conn.sock, mask, conn)
            except (KeyError, ValueError, OSError):
                pass

    def _shutdown_io(self):
        """Loop exit path: flush pending write buffers (bounded best
        effort), close every registered connection, the listener, the
        selector, and the waker."""
        with self._conn_lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for conn in conns:
            if conn.out:
                try:
                    conn.sock.setblocking(True)
                    conn.sock.settimeout(0.5)
                    for buf in conn.out:
                        conn.sock.sendall(buf)
                except (ConnectionError, OSError, socket.timeout):
                    pass
            try:
                conn.sock.close()
            except OSError:
                pass
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        if self._selector is not None:
            try:
                self._selector.close()
            except OSError:
                pass
        self._close_waker()


#: the selectable PS server cores (``ps_core=`` on the async trainers)
PS_CORES = {"event": SocketParameterServer,
            "threaded": ThreadedSocketParameterServer}


def make_socket_server(ps: ParameterServer, host: str = "127.0.0.1",
                       port: int = 0, generation: int = 0,
                       ps_core: str = "event", coalesce: bool = True,
                       idle_deadline: Optional[float] = None):
    """Construct the selected PS server core around ``ps``.  ``coalesce``
    only applies to the event core (the threaded core has no drain);
    ``idle_deadline`` enables half-open reaping on either core."""
    if ps_core not in PS_CORES:
        raise ValueError(
            f"ps_core must be one of {sorted(PS_CORES)}, got {ps_core!r}")
    if ps_core == "threaded":
        return ThreadedSocketParameterServer(ps, host=host, port=port,
                                             generation=generation,
                                             idle_deadline=idle_deadline)
    return SocketParameterServer(ps, host=host, port=port,
                                 generation=generation, coalesce=coalesce,
                                 idle_deadline=idle_deadline)


PS_CLASSES = {
    "downpour": DeltaParameterServer,
    "adag": ADAGParameterServer,
    "dynsgd": DynSGDParameterServer,
    "aeasgd": DeltaParameterServer,
    "eamsgd": DeltaParameterServer,
}

#: bind addresses that are listenable but not dialable — an advertise
#: host must never default to one of these
_WILDCARD_HOSTS = ("0.0.0.0", "::", "")


def resolve_ps_hosts(trainer) -> tuple:
    """The (bind, advertise) PS address pair for one training run.

    ``ps_bind_host`` is where the socket server listens; ``ps_advertise_host``
    is what the workers' config (and any ``attach_ps`` engine) dials.
    Advertise defaults to the bind host — except when the bind is a
    wildcard, which is listenable but not dialable, so the default falls
    back to loopback (multi-host callers bind ``"0.0.0.0"`` and advertise
    ``networking.determine_host_address()`` — docs/DEPLOY.md).  Both
    default to the historical loopback, bit for bit."""
    bind = getattr(trainer, "ps_bind_host", None) or "127.0.0.1"
    advertise = getattr(trainer, "ps_advertise_host", None)
    if advertise is None:
        advertise = "127.0.0.1" if bind in _WILDCARD_HOSTS else bind
    return bind, advertise


def allocate_parameter_server(algorithm: str, model_blob: dict,
                              num_workers: int,
                              apply_kernel: Optional[str] = None
                              ) -> ParameterServer:
    """Factory (reference: ``DistributedTrainer.allocate_parameter_server``)."""
    cls = PS_CLASSES[algorithm]
    if cls is ADAGParameterServer:
        return cls(model_blob, num_workers, apply_kernel=apply_kernel)
    return cls(model_blob, apply_kernel=apply_kernel)


def run_host_ps_training(trainer, dataset, shuffle: bool = False,
                         resume: bool = False) -> FittedModel:
    """Execute a DistributedTrainer with true async semantics: a live socket
    PS + one worker thread per "executor", each driving jitted window steps.

    This is the full reference execution model (SURVEY.md §3.1) on loopback —
    the analogue of Spark ``local[*]`` — and the same code path a multi-host
    DCN deployment uses with workers on other hosts pointing at
    ``determine_host_address()``.

    Checkpoint/resume (epoch granularity): training runs as epoch *waves* —
    all worker threads are joined between epochs, at which point the full
    async state (PS center weights + update clock + every worker's params
    and optimizer state) is consistent and serialized via ``Checkpointer``.
    Within an epoch commits stay truly asynchronous; bit-exact resume is a
    non-goal here (commit interleaving is scheduler-dependent by design —
    the deterministic path is ``execution='spmd'``).
    """
    algorithm = trainer.ALGORITHM
    if algorithm not in WORKER_CLASSES:
        raise ValueError(
            f"execution='host_ps' supports PS algorithms "
            f"{sorted(WORKER_CLASSES)}, not {algorithm!r} "
            f"({type(trainer).__name__})")
    if getattr(trainer, "checkpoint_unit", "epoch") == "round":
        raise ValueError(
            "checkpoint_unit='round' requires execution='spmd'; the host_ps "
            "path checkpoints at epoch waves")
    if resume and trainer.checkpoint_dir is None:
        raise ValueError("train(resume=True) needs checkpoint_dir")
    elastic = bool(getattr(trainer, "elastic", False))
    from .workers import parse_fault_injection
    fault_kinds = parse_fault_injection(getattr(trainer, "fault_injection",
                                                None))
    if elastic and (resume or trainer.checkpoint_dir is not None):
        raise ValueError(
            "elastic=True owns its own lease-based epoch loop and does not "
            "compose with checkpoint/resume yet — use elastic=False for "
            "checkpointed host_ps runs")
    if not elastic and any(k == "hang" for k, _ in fault_kinds.values()):
        raise ValueError(
            "fault_injection kind 'hang' wedges a worker until teardown; "
            "without elastic=True nothing ever revokes its work and the "
            "epoch join would deadlock — use elastic=True (or kinds "
            "'raise'/'exit')")

    trainer.record_training_start()
    trainer.failed_workers = []
    trainer.worker_failures = {}
    trainer.elastic_stats = {}
    x = np.asarray(dataset[trainer.features_col])
    y = np.asarray(dataset[trainer.label_col])
    if shuffle:
        perm = np.random.default_rng(trainer.seed).permutation(len(x))
        x, y = x[perm], y[perm]
    input_shape = x.shape[1:]
    params = trainer._initial_params(input_shape)
    blob = serialize_model(trainer.master_model, params)

    # reference parity (SURVEY §2.1 row 6): async trainers may run
    # parallelism_factor x num_workers concurrent tasks against the PS
    n = trainer.num_workers * getattr(trainer, "parallelism_factor", 1)
    ps_shards = int(getattr(trainer, "ps_shards", 1) or 1)
    recovery = bool(getattr(trainer, "recovery", False))
    # event-core knobs (docs/host_ps.md): ps_core selects the server
    # implementation (event default; "threaded" retains the seed core for
    # the worker-scaling comparison), coalesce gates drain merging, and
    # apply_kernel routes the scatter/axpy through csrc/applykernel.cpp
    ps_core = getattr(trainer, "ps_core", "event") or "event"
    coalesce = bool(getattr(trainer, "coalesce", True))
    apply_kernel = getattr(trainer, "apply_kernel", None)
    # recovery routes through the ShardedServerGroup for ANY shard count
    # (the N=1 plan is the identity partition, bit-identical per
    # tests/test_ps_sharding.py) so there is exactly one supervised
    # lifecycle: servers held in a mutable list the supervisor can respawn
    # into.  recovery=False keeps the PR 2 paths untouched.
    # PS address pair (docs/DEPLOY.md): bind where the server listens,
    # advertise what the workers dial — both loopback unless the trainer's
    # ps_bind_host/ps_advertise_host knobs say otherwise
    bind_host, advertise_host = resolve_ps_hosts(trainer)
    sharded = ps_shards > 1 or recovery
    if sharded:
        # PS sharding (ps_sharding.py): partition the center weight vector
        # over N shard servers — each wraps the UNCHANGED per-algorithm
        # apply rule on its slice, with its own apply lock and update clock,
        # so staleness semantics are per-shard identical to the single-PS
        # path and PS CPU/NIC bandwidth scales with the shard count
        server = ShardedServerGroup(algorithm, blob, n, ps_shards,
                                    host=bind_host,
                                    ps_core=ps_core, coalesce=coalesce,
                                    apply_kernel=apply_kernel)
        server.start()
    else:
        ps = allocate_parameter_server(algorithm, blob, n,
                                       apply_kernel=apply_kernel)
        server = make_socket_server(ps, host=bind_host, ps_core=ps_core,
                                    coalesce=coalesce)
        server.start()
    supervisor = None
    if recovery:
        # PS resilience (resilience.py): periodic per-shard snapshots +
        # heartbeat-driven respawn-from-snapshot on the same address.  The
        # workers below reconnect-resume under a RetryPolicy; windows
        # committed after a shard's last snapshot are dropped (bounded
        # loss, same class as worker staleness).
        from .resilience import ShardSupervisor
        supervisor = ShardSupervisor(server, algorithm, n)
        supervisor.start()
    trainer._ps_supervisor = supervisor  # observability (tests/bench)

    # deal rows round-robin per worker (Spark round-robin repartition
    # analogue): every row lands on exactly one worker, nothing dropped;
    # shard sizes differ by at most one row and the workers' own
    # window-padding absorbs the raggedness (one shared compilation)
    if len(x) < n:
        raise ValueError(
            f"dataset of {len(x)} rows has fewer rows than workers ({n})")
    xs = [x[i::n] for i in range(n)]
    ys = [y[i::n] for i in range(n)]

    worker_cls = WORKER_CLASSES[algorithm]
    kw = _worker_kwargs(trainer, n, len(x))
    kw.update(worker_optimizer=trainer.worker_optimizer,
              ps_host=advertise_host,
              ps_port=(server.ports[0] if sharded else server.port))
    rs = getattr(trainer, "row_sparse", None)
    if rs:
        # row-sparse embedding commits (streaming.py): resolve the knob
        # (True = every Embedding table in the model spec, or explicit
        # weight indices) against this run's params template
        from .streaming import resolve_row_sparse_tables
        kw.update(row_sparse_tables=resolve_row_sparse_tables(
            rs, trainer.master_model, params))
    if sharded:
        # workers scatter-commit / gather-pull through a ShardedPSClient
        # (one socket + one receive-buffer pool per shard).  _shard_addr_hook
        # lets chaos tests interpose a networking.ChaosProxy per shard — the
        # workers then drive the real socket stack through the proxy while
        # the supervisor heartbeats the shards directly.
        addrs = [(advertise_host, int(p)) for _, p in server.addrs]
        hook = getattr(trainer, "_shard_addr_hook", None)
        if hook is not None:
            addrs = [(str(h), int(p)) for h, p in hook(list(addrs))]
        kw.update(shard_plan=server.plan, shard_addrs=addrs)
    if recovery:
        kw.update(recovery=True,
                  retry_policy=getattr(trainer, "recovery_policy", None))

    if elastic:
        # elastic workers (resilience.py): lease-based shard redistribution,
        # death-respawn, and straggler stealing replace the static
        # round-robin deal + epoch-wave joins below
        try:
            workers = _run_elastic_host_ps(trainer, x, y, n, worker_cls,
                                           blob, kw)
        finally:
            if supervisor is not None:
                supervisor.stop()
            server.stop()
            trainer.ps_coalesce_stats = getattr(server, "coalesce_stats",
                                                None)
        trainer.history.clear()
        for w in workers:
            trainer.history.extend(w.history)
        fitted = server.get_model()
        trainer._fitted = fitted
        trainer.record_training_stop()
        return fitted

    workers = [worker_cls(blob, **kw) for _ in range(n)]
    share_compiled_state(workers)  # compile the window program once, not N×
    trainer._ps_workers = workers  # observability: transport counters (bench)

    ckpt = None
    start_epoch = 0
    states: List[Any] = [None] * n

    def full_state():
        """The complete async-training state as one host pytree.  Sharded
        runs store the GATHERED center plus the per-shard clock vector, so
        the checkpoint layout is shard-count-explicit (resume validates it
        against this run's ps_shards via the meta)."""
        if sharded:
            center, clocks = server.snapshot()
            clock = np.asarray(clocks, np.int64)
        else:
            with ps._lock:
                center = [w.copy() for w in ps.center]
                clock = np.int64(ps.num_updates)
        return {"center": center, "clock": clock,
                "workers": [jax.tree_util.tree_map(np.asarray, s)
                            for s in states]}

    try:
        if trainer.checkpoint_dir is not None:
            from .checkpoint import foreign_checkpoints, make_checkpointer
            backend = trainer.checkpoint_backend
            ckpt = make_checkpointer(trainer.checkpoint_dir, backend)
            latest = ckpt.latest_step()
            if resume and latest is None:
                foreign = foreign_checkpoints(trainer.checkpoint_dir, backend)
                if foreign:
                    raise ValueError(
                        f"resume=True with checkpoint_backend={backend!r}, "
                        f"but {trainer.checkpoint_dir} holds steps {foreign} "
                        "written by the other backend — resuming now would "
                        "silently retrain from scratch; use the backend that "
                        "wrote the checkpoints")
            if resume and latest is not None:
                # legacy pre-meta checkpoints were all spmd saves (host_ps
                # checkpointing used to raise NotImplementedError)
                meta = ckpt.read_meta(latest)
                if meta.get("engine", "spmd") != "host_ps":
                    raise ValueError(
                        f"checkpoint at {trainer.checkpoint_dir} was saved "
                        f"by engine={meta.get('engine', 'spmd')!r}; this "
                        "trainer is host_ps — resume with the same "
                        "configuration")
                if int(meta.get("ps_shards", 1)) != ps_shards:
                    raise ValueError(
                        f"checkpoint was saved with ps_shards="
                        f"{meta.get('ps_shards', 1)}; this trainer has "
                        f"ps_shards={ps_shards} — resume with the same "
                        "configuration")
                # template with the right pytree structure, then refill
                head = workers[0]
                p0 = head._weights_to_params(
                    server.snapshot()[0] if sharded else ps.center)
                states = [(p0, head._tx.init(p0)) for _ in range(n)]
                restored = ckpt.restore(full_state(), latest)
                if sharded:
                    server.restore_state(restored["center"],
                                         restored["clock"])
                else:
                    with ps._lock:
                        ps.center = [np.asarray(w, np.float32)
                                     for w in restored["center"]]
                        ps.num_updates = int(restored["clock"])
                states = [tuple(s) for s in restored["workers"]]
                start_epoch = latest

        # Without checkpointing there is no reason to barrier between
        # epochs: each worker runs all its epochs in one fully-async wave
        # (one connect, no stragglers at epoch joins) — the reference
        # execution model.  With a checkpoint_dir, epochs run as waves and
        # the joined state is saved.
        if ckpt is None:
            waves = [None]  # one wave, all epochs (worker default)
        else:
            waves = [(e, e + 1)
                     for e in range(start_epoch, trainer.num_epoch)]

        alive = [True] * n
        for epoch_range in waves:
            results: List[Optional[dict]] = [None] * n
            errors: List[tuple] = []

            def run(i, epoch_range=epoch_range):
                try:
                    results[i] = workers[i].train(
                        i,
                        {trainer.features_col: xs[i],
                         trainer.label_col: ys[i]},
                        initial_state=states[i],
                        epoch_range=epoch_range)
                except BaseException as e:  # propagate to the driver thread
                    errors.append((i, e))

            threads = [threading.Thread(target=run, args=(i,),
                                        name=f"dkt-worker-{i}")
                       for i in range(n) if alive[i]]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                # a dead SHARD is not a dead worker: it holds a partition of
                # the center that no survivor can reconstruct, so degraded
                # completion is impossible — surface it clearly regardless
                # of fault_tolerance
                shard_err = next((e for _, e in errors
                                  if isinstance(e, PSShardDown)), None)
                if shard_err is not None:
                    raise shard_err
                if not getattr(trainer, "fault_tolerance", False):
                    err = errors[0][1]
                    if isinstance(err, SystemExit):
                        # an 'exit'-faulted worker thread must surface as a
                        # training error, not exit the driver process
                        raise RuntimeError(
                            f"worker {errors[0][0]} exited: {err}") from err
                    raise err
                # degraded completion (SURVEY §5 fault table: reference
                # relied on Spark retry; we continue with survivors — the
                # center keeps every commit applied before the death).  A
                # tolerated death must stay diagnosable: keep the traceback
                # text on the trainer and say so on stderr.
                import sys
                import traceback
                for i, e in errors:
                    alive[i] = False
                    if i not in trainer.failed_workers:
                        trainer.failed_workers.append(i)
                        trainer.worker_failures[i] = "".join(
                            traceback.format_exception(e)).strip()
                    print(f"[distkeras_tpu] worker {i} died ({e!r}); "
                          "fault_tolerance: continuing with survivors",
                          file=sys.stderr)
                if not any(alive):
                    raise RuntimeError(
                        f"all {n} workers failed (fault_tolerance can "
                        "survive some, not all)") from errors[0][1]
            states = [r["state"] if r is not None else states[i]
                      for i, r in enumerate(results)]
            if ckpt is not None and (
                    epoch_range[1] % trainer.checkpoint_every == 0):
                ckpt.save(epoch_range[1], full_state(),
                          meta={"engine": "host_ps", "unit": "epoch",
                                "ps_shards": ps_shards})
    finally:
        if supervisor is not None:
            # stop the supervisor FIRST: the group teardown below must not
            # read as N shard deaths and trigger a respawn storm
            supervisor.stop()
        server.stop()
        # coalescing observability (bench host_ps_worker_scaling): counters
        # survive the stop; None on the threaded core
        trainer.ps_coalesce_stats = getattr(server, "coalesce_stats", None)
        if ckpt is not None:
            # durable async (orbax) saves + release the manager's
            # background threads — one leaks per train() otherwise
            ckpt.close()

    trainer.history.clear()
    for w in workers:
        trainer.history.extend(w.history)
    fitted = server.get_model()
    trainer._fitted = fitted
    trainer.record_training_stop()
    return fitted


def _run_elastic_host_ps(trainer, x, y, n: int, worker_cls, blob: dict,
                         kw: dict):
    """The elastic worker engine (``elastic=True`` — resilience.py).

    Replaces the static round-robin shard deal with a per-epoch
    ``LeaseLedger``: the epoch's rows are globally shuffled (deterministic
    in seed+epoch) and tiled into window-aligned leases that the worker
    threads acquire/renew/complete; a ``WorkerSupervisor`` revokes the
    leases of dead or wedged workers (survivors steal them) and respawns
    replacements under fresh ids from a live center pull.  After every
    epoch the ledger's exactly-once contract is asserted: killing k of N
    workers mid-epoch loses zero training examples.

    Returns the full worker list (original ids + respawns, id order) for
    history collection; resilience observability lands on the trainer as
    ``elastic_stats`` / ``failed_workers`` / ``worker_failures`` and
    ``_worker_supervisor``.
    """
    from .resilience import LeaseLedger, WorkerSupervisor

    win_rows = trainer.communication_window * trainer.batch_size
    total_windows = -(-len(x) // win_rows)
    lease_windows = getattr(trainer, "lease_windows", None)
    if lease_windows is None:
        # ~4 leases per worker per epoch: enough granularity for stealing
        # and respawn pickup without drowning in ledger round trips
        lease_windows = max(1, total_windows // (4 * n))
    head = worker_cls(blob, **kw)
    # compile the shared window program before the ledger clock starts (the
    # first lease's deadline must not pay the jit compile) and seed the
    # cold-start window estimate with the measured time: × n because the
    # real windows run under n-way thread contention.  The estimate is
    # generous by construction; each worker's EWMA tightens it from its
    # first renewal on.
    t_window = head.compile_windows(x, y)
    ledger = LeaseLedger(len(x), win_rows, lease_windows,
                         min_deadline=getattr(trainer, "lease_timeout", 5.0),
                         default_window_s=t_window * n)

    def factory(wid: int):
        w = head if wid == 0 else worker_cls(blob, **kw)
        share_compiled_state([head, w])  # one window program for everyone
        return w

    epoch_data: Dict[str, np.ndarray] = {}

    def run_fn(wid: int, worker):
        xe, ye = epoch_data["x"], epoch_data["y"]

        def data_fn(lease):
            return xe[lease.start:lease.stop], ye[lease.start:lease.stop]

        res = worker.train_leases(wid, ledger, data_fn,
                                  initial_state=sup.states.get(wid))
        sup.states[wid] = res["state"]
        return res

    sup = WorkerSupervisor(ledger, factory, run_fn, n)
    trainer._worker_supervisor = sup  # observability (tests/bench)
    epoch_reports = {}
    try:
        for epoch in range(trainer.num_epoch):
            # global per-epoch shuffle: leases are contiguous row ranges of
            # this permutation, so lease boundaries resample every epoch
            perm = np.random.default_rng(
                trainer.seed + 7919 * epoch).permutation(len(x))
            epoch_data["x"], epoch_data["y"] = x[perm], y[perm]
            sup.run_epoch(epoch)
            # the zero-data-loss contract, asserted per epoch
            epoch_reports[epoch] = ledger.assert_epoch_complete(epoch)
    finally:
        sup.shutdown()  # release 'hang'-faulted threads, join stragglers
        trainer.failed_workers = sorted(sup.failures)
        trainer.worker_failures = dict(sup.failures)
        trainer.elastic_stats = {
            "respawns": sup.respawns,
            "respawn_records": list(sup.respawn_records),
            "leases_reassigned": ledger.reassigned,
            "windows_per_worker": dict(ledger.windows_by_worker),
            "lease_completions": epoch_reports,
            "events": list(sup.events),
        }
        workers = [sup.workers[wid] for wid in sorted(sup.workers)]
        trainer._ps_workers = workers
    return workers


def _worker_kwargs(trainer, n: int, rows: int) -> dict:
    """Worker construction kwargs shared by the host (thread) and process
    PS engines — one place for the LR-schedule horizon formula and the
    elastic rho special-case.

    Schedule horizon per worker: the largest shard has ceil(rows/n) rows →
    windows/epoch × window mini-steps × epochs, ceil-divided by the
    accumulation factor (workers differ by at most one window).
    """
    accum = getattr(trainer, "gradient_accumulation", 1)
    win = trainer.communication_window
    shard_rows = -(-rows // n)
    windows_pe = -(-shard_rows // (win * trainer.batch_size))
    kw = dict(
        loss=trainer.loss, communication_window=win,
        features_col=trainer.features_col, label_col=trainer.label_col,
        batch_size=trainer.batch_size, num_epoch=trainer.num_epoch,
        learning_rate=trainer.learning_rate, seed=trainer.seed,
        lr_schedule=getattr(trainer, "lr_schedule", None),
        schedule_steps=-(-windows_pe * win * trainer.num_epoch // accum),
        gradient_accumulation=accum,
        gradient_clip_norm=getattr(trainer, "gradient_clip_norm", None),
        wire_dtype=getattr(trainer, "wire_dtype", None),
        wire_topk=getattr(trainer, "wire_topk", 0.01),
        wire_topk_dtype=getattr(trainer, "wire_topk_dtype", None),
        comm_overlap=getattr(trainer, "comm_overlap", False),
        fault_injection=getattr(trainer, "fault_injection", None))
    pw = int(getattr(trainer, "partition_windows", 0) or 0)
    if pw:
        kw["partition_windows"] = pw
    if trainer.ALGORITHM in ("aeasgd", "eamsgd"):
        kw["rho"] = getattr(trainer, "rho", 5.0)
    return kw


def _run_process_elastic(trainer, x, y, n: int, blob: dict, kw: dict,
                         optimizer, algorithm: str) -> FittedModel:
    """The supervised cross-process engine (``execution='process_ps'`` with
    ``elastic=True``) — ROADMAP item 1's simulated-DCN topology.

    Everything the in-process elastic engine proves in one interpreter runs
    here across real process boundaries: worker *processes* lease row ranges
    from a :class:`resilience.LeaseServer` over the wire, a
    :class:`resilience.ProcessSupervisor` detects SIGKILLed (waitpid) and
    SIGSTOPped (wire-heartbeat-silent) workers — revoking their leases so
    survivors steal the work, and respawning replacements under fresh ids
    through the :class:`job_deployment.Job` rail — and the per-epoch
    ``assert_epoch_complete`` keeps the zero-data-loss contract.

    The PS itself has two placements (``trainer.ps_placement``):

    - ``"driver"`` (default): a ``ShardedServerGroup`` inside this driver
      process — the PR 3 topology, now fed by worker processes.
    - ``"process"``: one ``ps_shard_main`` OS process per shard, each
      journaling to the shared scratch directory.  A shard that dies is
      respawned **same-address** by the supervisor; the fresh process
      restores its journal snapshot with its generation bumped, so
      in-flight commits against the pre-crash center are rejected by the
      existing generation handshake (bounded loss, zero protocol changes).

    The full dataset ships to every worker once (one npz in scratch); each
    epoch's global shuffle is reproduced bit-for-bit in every process from
    ``seed + 7919 * epoch``, so a lease's row range means the same rows
    everywhere — including to a replacement spawned mid-epoch.
    """
    import contextlib
    import glob as globmod
    import json
    import tempfile
    import time

    from .job_deployment import Job, LocalJobRunner
    from .ps_sharding import ShardedPSClient, make_shard_plan
    from .ps_worker_main import save_model_blob
    from .resilience import LeaseLedger, LeaseServer, ProcessSupervisor

    num_shards = int(getattr(trainer, "ps_shards", 1) or 1)
    placement = getattr(trainer, "ps_placement", "driver") or "driver"
    if placement not in ("driver", "process"):
        raise ValueError(
            f"ps_placement must be 'driver' or 'process', got {placement!r}")
    recovery = bool(getattr(trainer, "recovery", False))
    bind_host, advertise_host = resolve_ps_hosts(trainer)
    ps_core = getattr(trainer, "ps_core", "event") or "event"
    coalesce = bool(getattr(trainer, "coalesce", True))
    apply_kernel = getattr(trainer, "apply_kernel", None)

    # lease geometry — identical to the in-process elastic engine
    win_rows = trainer.communication_window * trainer.batch_size
    total_windows = -(-len(x) // win_rows)
    lease_windows = getattr(trainer, "lease_windows", None)
    if lease_windows is None:
        lease_windows = max(1, total_windows // (4 * n))
    # cold-start deadline seed: compile + time the same window program the
    # workers will build, × n for contention (their first window pays their
    # own per-process compile; each worker's EWMA tightens from renewal #1)
    head = WORKER_CLASSES[algorithm](
        blob, worker_optimizer=trainer.worker_optimizer,
        ps_host=advertise_host, ps_port=0, **kw)
    t_window = head.compile_windows(x, y)
    ledger = LeaseLedger(len(x), win_rows, lease_windows,
                         min_deadline=getattr(trainer, "lease_timeout", 5.0),
                         default_window_s=t_window * n)

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {"PYTHONPATH": os.pathsep.join(
        p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p)}

    with contextlib.ExitStack() as stack:
        scratch = getattr(trainer, "scratch_dir", None)
        if scratch is None:
            scratch = stack.enter_context(
                tempfile.TemporaryDirectory(prefix="dkt_procel_"))
        else:
            os.makedirs(scratch, exist_ok=True)
        model_path = os.path.join(scratch, "model.npz")
        save_model_blob(model_path, blob)
        data_path = os.path.join(scratch, "data.npz")
        np.savez(data_path, x=x, y=y)
        result_dir = os.path.join(scratch, "results")
        os.makedirs(result_dir, exist_ok=True)

        # -- bring up the PS ------------------------------------------------
        group = None
        ps_procs: List[Any] = []
        respawn_ps = None
        if placement == "driver":
            group = ShardedServerGroup(algorithm, blob, n, num_shards,
                                       host=bind_host, ps_core=ps_core,
                                       coalesce=coalesce,
                                       apply_kernel=apply_kernel)
            group.start()
            stack.callback(group.stop)
            shard_addrs = [(advertise_host, int(p))
                           for _, p in group.addrs]
        else:
            from .ps_shard_main import read_addr
            addr_dir = os.path.join(scratch, "addrs")
            journal_dir = os.path.join(scratch, "journal")
            os.makedirs(addr_dir, exist_ok=True)
            os.makedirs(journal_dir, exist_ok=True)
            ps_cfg_path = os.path.join(scratch, "shard_config.json")
            with open(ps_cfg_path, "w") as f:
                json.dump({
                    "algorithm": algorithm, "model_path": model_path,
                    "num_workers": n, "num_shards": num_shards,
                    "bind_host": bind_host, "addr_dir": addr_dir,
                    "journal_dir": journal_dir, "ps_core": ps_core,
                    "coalesce": coalesce, "apply_kernel": apply_kernel,
                    "snapshot_interval":
                        getattr(trainer, "snapshot_interval", 0.5),
                }, f)

            def spawn_shard(j: int):
                job = Job(name=f"{algorithm}-ps-shard{j}", script="-m",
                          args=["distkeras_tpu.ps_shard_main", ps_cfg_path,
                                str(j)],
                          hosts=["127.0.0.1"], env=env, coordinated=False)
                job.run(LocalJobRunner(), wait=False)
                return job.processes[0]

            respawn_ps = spawn_shard
            ps_procs = [spawn_shard(j) for j in range(num_shards)]

            def _stop_shards():
                procs = (trainer._process_supervisor.ps_procs
                         if getattr(trainer, "_process_supervisor", None)
                         else ps_procs)
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                for p in procs:
                    try:
                        p.wait(timeout=15)
                    except Exception:
                        p.kill()

            stack.callback(_stop_shards)
            shard_addrs = []
            deadline = time.monotonic() + 180  # cold jax imports
            for j in range(num_shards):
                path = os.path.join(addr_dir, f"shard_{j}.addr")
                while not os.path.exists(path):
                    if ps_procs[j].poll() is not None:
                        raise RuntimeError(
                            f"PS shard process {j} exited with code "
                            f"{ps_procs[j].returncode} before publishing "
                            "its address")
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            f"PS shard process {j} never published its "
                            "address")
                    time.sleep(0.05)
                h, port, _gen = read_addr(path)
                shard_addrs.append((advertise_host if h in _WILDCARD_HOSTS
                                    else h, port))

        # -- the lease rail --------------------------------------------------
        lease_server = stack.enter_context(LeaseServer(ledger,
                                                       host=bind_host))

        wcfg = {**kw, "algorithm": algorithm, "model_path": model_path,
                "data_path": data_path, "result_dir": result_dir,
                "worker_optimizer": optimizer,
                "lease_host": advertise_host,
                "lease_port": lease_server.port,
                "ps_host": shard_addrs[0][0], "ps_port": shard_addrs[0][1]}
        if num_shards > 1:
            wcfg["num_shards"] = num_shards
            wcfg["shard_addrs"] = [[h, p] for h, p in shard_addrs]
        if recovery:
            wcfg["recovery"] = True
        pw = int(getattr(trainer, "partition_windows", 0) or 0)
        if pw:
            wcfg["partition_windows"] = pw
            wcfg["recovery"] = True  # heal-exhaustion falls back to resume
        wcfg_path = os.path.join(scratch, "worker_config.json")
        with open(wcfg_path, "w") as f:
            json.dump(wcfg, f)

        def spawn_worker(wid: int):
            job = Job(name=f"{algorithm}-elastic-w{wid}", script="-m",
                      args=["distkeras_tpu.ps_worker_main", wcfg_path,
                            str(wid)],
                      hosts=["127.0.0.1"], env=env, coordinated=False,
                      process_ids=[wid])
            job.run(LocalJobRunner(), wait=False)
            return job.processes[0]

        sup = ProcessSupervisor(
            ledger, lease_server, spawn_worker, n,
            freeze_deadline=getattr(trainer, "freeze_deadline", None),
            max_respawns=getattr(trainer, "max_respawns", None),
            ps_procs=ps_procs or None,
            ps_addrs=shard_addrs if ps_procs else None,
            respawn_ps=respawn_ps)
        trainer._process_supervisor = sup  # observability (tests/bench)

        epoch_reports: Dict[int, Any] = {}
        try:
            sup.start()
            for epoch in range(trainer.num_epoch):
                sup.run_epoch(epoch)
                # the zero-data-loss contract, asserted per epoch
                epoch_reports[epoch] = ledger.assert_epoch_complete(epoch)
        finally:
            sup.shutdown()
            trainer.failed_workers = sorted(sup.failures)
            trainer.worker_failures = dict(sup.failures)
            trainer.elastic_stats = {**sup.stats(),
                                     "lease_completions": epoch_reports}

        # histories from every worker that ever ran (original ids +
        # respawned fresh ids), id order — files are globbed because
        # replacements land under ids the launch config never knew
        trainer.history.clear()
        results = globmod.glob(os.path.join(result_dir, "result_*.npz"))
        for p in sorted(results, key=lambda q: int(
                os.path.basename(q)[len("result_"):-len(".npz")])):
            with np.load(p) as z:
                trainer.history.extend(z["history"].tolist())

        # -- final model -----------------------------------------------------
        if group is not None:
            trainer.ps_coalesce_stats = group.coalesce_stats
            fitted = group.get_model()
        else:
            # gather the final center over the wire before retiring the
            # shard processes (the ExitStack SIGTERMs them on the way out;
            # each journals a final snapshot — clean handoff)
            trainer.ps_coalesce_stats = None
            weights = [np.asarray(w) for w in blob["weights"]]
            plan = make_shard_plan([w.shape for w in weights],
                                   [w.dtype for w in weights], num_shards)
            client = ShardedPSClient(plan, shard_addrs, recovery=True)
            try:
                client.connect()
                center = client.pull()
            finally:
                client.disconnect()
            model, params = deserialize_model(
                {"model": blob["model"], "weights": center})
            fitted = FittedModel(model, params)

    trainer._fitted = fitted
    trainer.record_training_stop()
    return fitted


def run_process_ps_training(trainer, dataset, shuffle: bool = False
                            ) -> FittedModel:
    """Execute a DistributedTrainer with workers as separate OS PROCESSES.

    This is the actual reference topology (SURVEY.md §3.1): the driver
    process hosts the socket PS; each worker is its own interpreter,
    launched with ``job_deployment.LocalJobRunner`` on loopback.  Unlike
    ``execution='host_ps'`` (threads in one interpreter, GIL-shared), the
    workers here share nothing but the TCP socket: the test proof that the
    wire protocol, and not thread memory sharing, carries training.

    Workers are launched *uncoordinated* (``Job(coordinated=False)``): PS
    clients never use collectives, and a shared ``jax.distributed`` group
    would stall the healthy workers at the init barrier if one died.

    Model blob and per-worker shards travel via a driver-local scratch
    directory (the Spark analogue: closure + partition shipping);
    histories return the same way.  A real multi-host DCN deployment keeps
    the same ``ps_worker_main`` entry point and ``DISTKERAS_TPU_*`` env
    contract via ``SSHJobRunner``, but additionally needs a shared scratch
    path and a PS bound on a routable interface — same-host processes are
    what this function wires up today.  Checkpoint/resume stays on the
    in-process engines.
    """
    import json
    import tempfile

    from .job_deployment import Job, LocalJobRunner
    from .ps_worker_main import save_model_blob

    algorithm = trainer.ALGORITHM
    if algorithm not in WORKER_CLASSES:
        raise ValueError(
            f"execution='process_ps' supports PS algorithms "
            f"{sorted(WORKER_CLASSES)}, not {algorithm!r} "
            f"({type(trainer).__name__})")
    if trainer.checkpoint_dir is not None:
        raise ValueError(
            "checkpoint/resume is not supported on execution='process_ps' "
            "(use 'host_ps' for epoch-wave checkpoints)")
    from .workers import parse_fault_injection
    if not getattr(trainer, "elastic", False) and any(
            k == "hang" for k, _ in parse_fault_injection(
                getattr(trainer, "fault_injection", None)).values()):
        raise ValueError(
            "fault_injection kind 'hang' wedges a worker process forever; "
            "the static process engine has no lease ledger to revoke its "
            "work — use elastic=True (any execution) so the leases of a "
            "wedged worker are revoked and stolen")

    trainer.record_training_start()
    trainer.failed_workers = []
    trainer.worker_failures = {}
    x = np.asarray(dataset[trainer.features_col])
    y = np.asarray(dataset[trainer.label_col])
    if shuffle:
        perm = np.random.default_rng(trainer.seed).permutation(len(x))
        x, y = x[perm], y[perm]
    params = trainer._initial_params(x.shape[1:])
    blob = serialize_model(trainer.master_model, params)

    n = trainer.num_workers * getattr(trainer, "parallelism_factor", 1)
    if len(x) < n:
        raise ValueError(
            f"dataset of {len(x)} rows has fewer rows than workers ({n})")
    # all validation/config prep BEFORE the server starts: an error here
    # must not leak the listener thread
    optimizer = trainer.worker_optimizer
    if not isinstance(optimizer, str):  # Optimizer object → JSON config
        optimizer = optimizer.get_config()
    kw = _worker_kwargs(trainer, n, len(x))
    if callable(kw["lr_schedule"]):
        raise ValueError(
            "execution='process_ps' cannot ship a callable lr_schedule to "
            "worker processes — pass a name or config dict "
            "(e.g. 'warmup_cosine'), or use execution='host_ps'")

    if getattr(trainer, "elastic", False):
        # the supervised cross-process engine: lease rail + process-level
        # supervision + (optionally) PS shards as their own OS processes
        return _run_process_elastic(trainer, x, y, n, blob, kw, optimizer,
                                    algorithm)

    num_shards = int(getattr(trainer, "ps_shards", 1) or 1)
    bind_host, advertise_host = resolve_ps_hosts(trainer)
    if num_shards > 1:
        # sharded static path: the driver hosts a ShardedServerGroup and
        # the worker processes scatter/gather through a ShardedPSClient —
        # the process boundary is invisible to the shard wire protocol
        server = ShardedServerGroup(
            algorithm, blob, n, num_shards, host=bind_host,
            ps_core=getattr(trainer, "ps_core", "event") or "event",
            coalesce=bool(getattr(trainer, "coalesce", True)),
            apply_kernel=getattr(trainer, "apply_kernel", None))
    else:
        ps = allocate_parameter_server(
            algorithm, blob, n,
            apply_kernel=getattr(trainer, "apply_kernel", None))
        server = make_socket_server(
            ps, host=bind_host,
            ps_core=getattr(trainer, "ps_core", "event") or "event",
            coalesce=bool(getattr(trainer, "coalesce", True)))
    server.start()
    try:
        with tempfile.TemporaryDirectory(prefix="dkt_procps_") as tmp:
            model_path = os.path.join(tmp, "model.npz")
            save_model_blob(model_path, blob)
            shard_paths, result_paths = [], []
            for i in range(n):  # round-robin deal, as the thread engine
                p = os.path.join(tmp, f"shard_{i}.npz")
                np.savez(p, x=x[i::n], y=y[i::n])
                shard_paths.append(p)
                result_paths.append(os.path.join(tmp, f"result_{i}.npz"))
            cfg_path = os.path.join(tmp, "worker_config.json")
            if num_shards > 1:
                endpoint = {
                    "ps_host": advertise_host,
                    "ps_port": server.ports[0],
                    "num_shards": num_shards,
                    "shard_addrs": [[advertise_host, int(p)]
                                    for _, p in server.addrs],
                }
            else:
                endpoint = {"ps_host": advertise_host,
                            "ps_port": server.port}
            with open(cfg_path, "w") as f:
                json.dump({
                    **kw,
                    **endpoint,
                    "algorithm": algorithm,
                    "model_path": model_path,
                    "shard_paths": shard_paths,
                    "result_paths": result_paths,
                    "worker_optimizer": optimizer,
                }, f)

            # repo root on PYTHONPATH so `-m distkeras_tpu.ps_worker_main`
            # resolves in the child even without an installed package
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = {"PYTHONPATH": os.pathsep.join(
                p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p)}
            job = Job(name=f"{algorithm}-process-ps", script="-m",
                      args=["distkeras_tpu.ps_worker_main", cfg_path],
                      hosts=["127.0.0.1"] * n, env=env, coordinated=False)
            job.run(LocalJobRunner())
            # max() would mask signal deaths (negative codes) behind a 0
            failed = [i for i, c in enumerate(job.returncodes) if c != 0]
            if failed:
                if not getattr(trainer, "fault_tolerance", False):
                    raise RuntimeError(
                        f"worker process failed (exit codes "
                        f"{job.returncodes})")
                if len(failed) == n:
                    raise RuntimeError(
                        f"all {n} worker processes failed (exit codes "
                        f"{job.returncodes}); fault_tolerance can survive "
                        "some, not all")
                # degraded completion: the PS already holds every commit
                # the dead workers applied before dying (their EOF was a
                # normal disconnect to the server).  Keep the exit codes
                # diagnosable and say so on stderr.
                import sys
                trainer.failed_workers = failed
                for i in failed:
                    trainer.worker_failures[i] = (
                        f"exit code {job.returncodes[i]}")
                print(f"[distkeras_tpu] worker processes {failed} exited "
                      f"nonzero ({job.returncodes}); fault_tolerance: "
                      "continuing with survivors", file=sys.stderr)

            trainer.history.clear()
            for i, p in enumerate(result_paths):
                if i in failed:
                    continue  # no result file from a dead worker
                with np.load(p) as z:
                    trainer.history.extend(z["history"].tolist())
    finally:
        server.stop()
        trainer.ps_coalesce_stats = getattr(server, "coalesce_stats", None)

    fitted = server.get_model()
    trainer._fitted = fitted
    trainer.record_training_stop()
    return fitted
