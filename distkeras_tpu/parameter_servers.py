"""Host parameter servers — the semantically-exact asynchronous path.

Reference being replaced: ``distkeras/parameter_servers.py`` (SURVEY.md §2.1
rows 14–16, §3.4): a TCP server thread on the Spark driver holding the center
model; one handler thread per worker connection; 1-byte actions ``'p'``
(pull → send center weights) and ``'c'`` (commit → apply delta).  The
reference applies commits **without a lock** (GIL-tolerated hogwild); we keep
true hogwild *interleaving* across windows but make each individual apply
atomic under a mutex — same algorithm semantics, no torn ndarray writes.

Where this fits in the TPU design: the primary execution engine is the
bulk-synchronous SPMD program over ICI (``parallel/spmd.py``).  This module is
selected with ``Trainer(..., execution='host_ps')`` and exists because true
asynchronous staleness (DOWNPOUR/DynSGD semantics) is *not representable*
inside a single XLA program — so it runs on the host side over DCN/loopback,
with each worker thread driving jitted window steps on its device.  Update
rules mirror the pure functions in ``parallel/rules.py``, applied here as
in-place numpy loops on flat weight lists for commit-path speed;
tests/test_host_ps.py asserts the two implementations agree.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import networking
from .core.model import FittedModel, deserialize_model, serialize_model
from .ps_sharding import PSShardDown, ShardedServerGroup
from .workers import WORKER_CLASSES, share_compiled_state

logger = logging.getLogger("distkeras_tpu.parameter_servers")


def _as_f32(delta):
    """Upcast wire deltas (possibly bf16-compressed by the worker's
    ``wire_dtype`` — see ``workers.PSWorker.commit``) to the center's f32."""
    return [np.asarray(d).astype(np.float32, copy=False) for d in delta]


def _scatter_add(center: List[np.ndarray], sp: "networking.SparseDelta",
                 scale: float = 1.0) -> None:
    """Apply a k-sparse flat delta to a tensor list: O(k) scatter-add.

    ``sp`` indexes the concatenation of ``center`` (C-order flat, list
    order); indices are validated against the dense length so a hostile or
    mis-split commit raises instead of corrupting neighbouring tensors.
    Sorted indices are bisected once over the tensor offsets, then each
    tensor gets one ``np.add.at`` over its contiguous index run — the
    whole apply touches k coordinates, not the n-element center.
    """
    sizes = np.array([int(c.size) for c in center], np.int64)
    offsets = np.concatenate(([0], np.cumsum(sizes)))
    total = int(offsets[-1])
    if sp.length != total:
        raise ValueError(
            f"sparse commit declares dense length {sp.length}, center "
            f"has {total} elements")
    idx = sp.indices.astype(np.int64, copy=False)
    vals = sp.f32_values()
    if idx.size == 0:
        return
    if np.any(np.diff(idx) < 0):  # tolerate unsorted senders
        order = np.argsort(idx, kind="stable")
        idx, vals = idx[order], vals[order]
    if idx[0] < 0 or idx[-1] >= total:
        raise ValueError(
            f"sparse commit index out of range for dense length {total}")
    if scale != 1.0:
        vals = vals * np.float32(scale)
    bounds = np.searchsorted(idx, offsets)
    for t in range(len(center)):
        lo, hi = int(bounds[t]), int(bounds[t + 1])
        if lo == hi:
            continue
        flat = center[t].reshape(-1)  # view: center tensors are contiguous
        np.add.at(flat, idx[lo:hi] - int(offsets[t]), vals[lo:hi])


class ParameterServer:
    """Base PS (reference: ``parameter_servers.py :: ParameterServer``):
    holds the center weights + the update clock."""

    def __init__(self, model_blob: dict):
        self.model_blob = model_blob
        self.center: List[np.ndarray] = [
            np.array(w, dtype=np.float32, copy=True)
            for w in model_blob["weights"]]
        self.num_updates = 0
        # the APPLY lock: guards center + clock only.  Connection
        # bookkeeping lives behind SocketParameterServer's own lock, so N
        # workers' commits never serialize behind accept/teardown state.
        self._lock = threading.Lock()

    def initialize(self):
        """Reference-parity hook (center is built in __init__ here)."""

    def next_update(self) -> int:
        self.num_updates += 1
        return self.num_updates

    def get_model(self) -> FittedModel:
        model, params = deserialize_model(
            {"model": self.model_blob["model"], "weights": self.center})
        return FittedModel(model, params)

    # -- the per-algorithm apply rule (subclasses override _apply) -----------
    def _apply(self, msg: Dict[str, Any]):
        """Apply one commit to the center.  Called with ``_lock`` HELD."""
        raise NotImplementedError

    def _apply_scaled(self, msg: Dict[str, Any], scale: float):
        """Shared commit arithmetic: ``center += scale * delta`` for a dense
        tensor list, or an O(k) scatter-add for a k-sparse commit
        (``networking.SparseDelta`` — the ``wire_dtype="topk"`` wire form).
        Every rule reduces to a scalar ``scale``, so sparsity composes with
        all of them under the same apply lock."""
        delta = msg["delta"]
        if isinstance(delta, networking.SparseDelta):
            _scatter_add(self.center, delta, scale)
        elif scale == 1.0:
            for c, d in zip(self.center, _as_f32(delta)):
                c += d
        else:
            for c, d in zip(self.center, _as_f32(delta)):
                c += scale * d
        self.next_update()

    def handle_commit(self, msg: Dict[str, Any]):
        with self._lock:
            self._apply(msg)

    def handle_update(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """``'u'`` = commit+pull: apply the delta and snapshot center+clock
        under ONE lock acquisition, so the reply is exactly the center this
        commit produced (plus any commits that landed before it) — the
        atomic combined round trip the overlapped workers ride."""
        with self._lock:
            self._apply(msg)
            return {"weights": [w.copy() for w in self.center],
                    "clock": self.num_updates}

    def handle_pull(self) -> Dict[str, Any]:
        with self._lock:
            return {"weights": [w.copy() for w in self.center],
                    "clock": self.num_updates}

    def handle_heartbeat(self) -> Dict[str, Any]:
        """``'h'``: cheap liveness probe — clock only, no weights.  Goes
        through the apply lock *deliberately*: a shard wedged inside an
        apply must fail the heartbeat deadline, not answer "alive" while
        every commit stalls (resilience.ShardSupervisor)."""
        with self._lock:
            return {"clock": self.num_updates}


class DeltaParameterServer(ParameterServer):
    """center += delta (reference: ``DeltaParameterServer`` — DOWNPOUR's and
    the elastic family's PS; for EASGD the committed 'delta' is the elastic
    term, so the same rule applies)."""

    def _apply(self, msg):
        self._apply_scaled(msg, 1.0)


class ADAGParameterServer(ParameterServer):
    """ADAG normalization (reference: ``ADAGParameterServer``): accumulated
    deltas are normalized over the number of concurrent committers before
    applying — the per-commit form of ``rules.adag_commit`` (which divides
    the cross-worker sum by the worker count)."""

    def __init__(self, model_blob, num_workers: int):
        super().__init__(model_blob)
        self.num_workers = max(int(num_workers), 1)

    def _apply(self, msg):
        self._apply_scaled(msg, 1.0 / self.num_workers)


class DynSGDParameterServer(ParameterServer):
    """Staleness-aware apply (reference: ``DynSGDParameterServer``):
    center += delta / (staleness + 1), where staleness = updates that landed
    since this worker's last pull (the commit's ``clock`` field) — exactly
    ``rules.dynsgd_commit``."""

    def _apply(self, msg):
        staleness = max(self.num_updates - int(msg.get("clock", 0)), 0)
        self._apply_scaled(msg, 1.0 / (staleness + 1.0))


class SocketParameterServer:
    """TCP accept-loop wrapper around a ParameterServer (reference:
    ``SocketParameterServer.run`` — thread per connection, opcode dispatch).

    Composition instead of inheritance so the apply rules above stay pure-ish
    and unit-testable without sockets.
    """

    def __init__(self, ps: ParameterServer, host: str = "127.0.0.1",
                 port: int = 0, generation: int = 0):
        self.ps = ps
        self.host = host
        self.port = port  # 0 → ephemeral; real port set by start()
        # recovery epoch (resilience.ShardSupervisor): bumped on every
        # respawn of this address.  Replies carry it; commits stamped with
        # an older generation are rejected (they were computed against a
        # center this restart rolled back) — the epoch/generation handshake.
        self.generation = int(generation)
        self._server: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: List[socket.socket] = []
        self._conn_of: Dict[threading.Thread, socket.socket] = {}
        self._conn_lock = threading.Lock()  # guards _conns/_conn_threads/_running
        self._running = False

    # -- lifecycle (reference: initialize/start/stop) ------------------------
    def start(self):
        self.ps.initialize()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self.host, self.port))
        self.port = self._server.getsockname()[1]
        self._server.listen(128)
        self._running = True
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="dkt-ps-accept")
        self._accept_thread.start()

    def stop(self, join_timeout: float = 5.0):
        """Idempotent shutdown that actually unblocks every thread.

        Closing an fd from another thread does not reliably interrupt a
        blocked ``accept()`` on Linux, so we wake the accept loop with a
        self-connection, join it, then ``shutdown(SHUT_RDWR)`` every accepted
        connection to kick handler threads out of ``recv`` before joining
        them.  A handler that outlives its ``join_timeout`` (wedged inside
        an apply, not a recv) is no longer leaked silently: the leak is
        logged and its connection socket force-closed again, so a thread
        stuck in socket I/O unblocks and one stuck in compute at least
        fails fast on its next send instead of writing to a live peer.
        """
        with self._conn_lock:
            was_running = self._running
            self._running = False
        if was_running and self._server is not None:
            try:  # wake the blocked accept(); loop sees _running=False
                wake = socket.create_connection((self.host, self.port),
                                                timeout=1.0)
                wake.close()
            except OSError:
                pass  # server socket already dead — accept has returned
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass
        with self._conn_lock:
            conns, threads = list(self._conns), list(self._conn_threads)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=join_timeout)
            if t.is_alive():
                logger.warning(
                    "PS handler thread %s still alive after stop(join_"
                    "timeout=%.1fs) — likely wedged in an apply; force-"
                    "closing its connection and leaving it to die detached",
                    t.name, join_timeout)
                with self._conn_lock:
                    conn = self._conn_of.get(t)
                if conn is not None:
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass

    @property
    def live_connections(self) -> int:
        """Connections with a live handler thread — the bookkeeping a
        half-frame worker death must decrement (a dying worker's torn
        commit drops its connection silently: no codec error escapes the
        handler, no `_conns` entry leaks; tests/test_elastic_workers.py)."""
        with self._conn_lock:
            return len(self._conns)

    def crash(self):
        """Abrupt-death simulation (chaos/bench hook): close the listener
        and every connection with no graceful shutdown, no joins, no final
        state flush — the in-process analogue of a SIGKILLed shard.  The
        in-memory center is deliberately abandoned; recovery must come from
        the last journal snapshot (resilience.ShardSupervisor), which is
        exactly the bounded-loss contract under test."""
        with self._conn_lock:
            self._running = False
            conns = list(self._conns)
        if self._server is not None:
            # shutdown() interrupts a blocked accept() (close() alone does
            # not on Linux — the accept syscall pins the open file
            # description, which would keep the PORT bound and block a
            # same-address respawn with EADDRINUSE)
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        for c in conns:
            networking._hard_close(c)

    def get_model(self) -> FittedModel:
        return self.ps.get_model()

    # -- service loops -------------------------------------------------------
    def _accept_loop(self):
        while True:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return  # socket closed by stop()
            with self._conn_lock:
                if not self._running:  # stop()'s wake connection, or late join
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                t = threading.Thread(
                    target=self._handle_connection, args=(conn,),
                    daemon=True, name="dkt-ps-conn")
                self._conns.append(conn)
                self._conn_threads.append(t)
                self._conn_of[t] = conn
            t.start()

    def _handle_connection(self, conn: socket.socket):
        """Reference: ``handle_connection`` — loop on 1-byte actions until
        EOF/quit ('p' pull, 'c' commit, 'u' commit+pull, 'h' heartbeat,
        'q' quit).  Every reply carries this server's ``generation``."""
        # per-connection send pool: replies (full center, fixed layout)
        # re-serialize into the same preallocated buffer every round trip
        # instead of allocating a weight-sized output blob per reply
        send_pool = networking.BufferPool()
        try:
            while True:
                op = networking.recv_opcode(conn)
                if op in (b"", b"q"):
                    return
                if op == b"p":
                    reply = self.ps.handle_pull()
                    reply["gen"] = self.generation
                    networking.send_data(conn, reply, pool=send_pool)
                elif op == b"h":
                    # liveness probe (resilience.ShardSupervisor): clock +
                    # generation, no weights — and it takes the apply lock,
                    # so a wedged apply fails the probe deadline
                    reply = self.ps.handle_heartbeat()
                    reply["gen"] = self.generation
                    networking.send_data(conn, reply, pool=send_pool)
                elif op in (b"c", b"u"):
                    try:
                        msg = networking.recv_data(conn)
                    except ValueError:
                        return  # torn/corrupt frame: drop the connection
                    if isinstance(msg, dict) and "scales" in msg:
                        # int8 wire compression (workers.PSWorker.commit):
                        # codes x per-tensor scale -> f32 delta, decoded at
                        # the transport boundary so every PS rule sees
                        # ordinary float deltas
                        msg["delta"] = [
                            np.asarray(q, np.float32) * s
                            for q, s in zip(msg["delta"], msg.pop("scales"))]
                    elif (isinstance(msg, dict) and
                          isinstance(msg.get("delta"),
                                     networking.SparseDelta)):
                        # sparse top-k commit: dequantize the (possibly
                        # bf16/int8-coded) values to f32 at the same
                        # transport boundary — apply rules see f32 values
                        # and scatter-add in O(k)
                        msg["delta"] = msg["delta"].decoded()
                    # generation handshake: a commit stamped with an older
                    # generation was computed against a center a restart
                    # rolled back — drop it (bounded loss, same class as
                    # worker staleness) instead of applying it to the
                    # restored center.  'u' still replies with the current
                    # state + generation so the worker re-syncs in the same
                    # round trip.
                    gen = msg.get("gen") if isinstance(msg, dict) else None
                    stale = gen is not None and int(gen) != self.generation
                    # apply-rule errors deliberately propagate (visible
                    # thread traceback) — only transport faults are silent
                    if op == b"c":
                        if not stale:
                            self.ps.handle_commit(msg)
                    else:
                        # 'u': apply + snapshot atomically, reply in the
                        # same round trip (one DCN RTT per window instead
                        # of a commit send followed by a pull round trip)
                        if stale:
                            reply = self.ps.handle_pull()
                            reply["stale"] = True
                        else:
                            reply = self.ps.handle_update(msg)
                        reply["gen"] = self.generation
                        networking.send_data(conn, reply, pool=send_pool)
                else:
                    return  # protocol violation: drop the connection
        except (ConnectionError, OSError):
            # worker died: reference behavior is silent handler exit; the
            # server keeps serving the others
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            me = threading.current_thread()
            with self._conn_lock:
                if conn in self._conns:
                    self._conns.remove(conn)
                if me in self._conn_threads:
                    self._conn_threads.remove(me)
                self._conn_of.pop(me, None)


PS_CLASSES = {
    "downpour": DeltaParameterServer,
    "adag": ADAGParameterServer,
    "dynsgd": DynSGDParameterServer,
    "aeasgd": DeltaParameterServer,
    "eamsgd": DeltaParameterServer,
}


def allocate_parameter_server(algorithm: str, model_blob: dict,
                              num_workers: int) -> ParameterServer:
    """Factory (reference: ``DistributedTrainer.allocate_parameter_server``)."""
    cls = PS_CLASSES[algorithm]
    if cls is ADAGParameterServer:
        return cls(model_blob, num_workers)
    return cls(model_blob)


def run_host_ps_training(trainer, dataset, shuffle: bool = False,
                         resume: bool = False) -> FittedModel:
    """Execute a DistributedTrainer with true async semantics: a live socket
    PS + one worker thread per "executor", each driving jitted window steps.

    This is the full reference execution model (SURVEY.md §3.1) on loopback —
    the analogue of Spark ``local[*]`` — and the same code path a multi-host
    DCN deployment uses with workers on other hosts pointing at
    ``determine_host_address()``.

    Checkpoint/resume (epoch granularity): training runs as epoch *waves* —
    all worker threads are joined between epochs, at which point the full
    async state (PS center weights + update clock + every worker's params
    and optimizer state) is consistent and serialized via ``Checkpointer``.
    Within an epoch commits stay truly asynchronous; bit-exact resume is a
    non-goal here (commit interleaving is scheduler-dependent by design —
    the deterministic path is ``execution='spmd'``).
    """
    algorithm = trainer.ALGORITHM
    if algorithm not in WORKER_CLASSES:
        raise ValueError(
            f"execution='host_ps' supports PS algorithms "
            f"{sorted(WORKER_CLASSES)}, not {algorithm!r} "
            f"({type(trainer).__name__})")
    if getattr(trainer, "checkpoint_unit", "epoch") == "round":
        raise ValueError(
            "checkpoint_unit='round' requires execution='spmd'; the host_ps "
            "path checkpoints at epoch waves")
    if resume and trainer.checkpoint_dir is None:
        raise ValueError("train(resume=True) needs checkpoint_dir")
    elastic = bool(getattr(trainer, "elastic", False))
    from .workers import parse_fault_injection
    fault_kinds = parse_fault_injection(getattr(trainer, "fault_injection",
                                                None))
    if elastic and (resume or trainer.checkpoint_dir is not None):
        raise ValueError(
            "elastic=True owns its own lease-based epoch loop and does not "
            "compose with checkpoint/resume yet — use elastic=False for "
            "checkpointed host_ps runs")
    if not elastic and any(k == "hang" for k, _ in fault_kinds.values()):
        raise ValueError(
            "fault_injection kind 'hang' wedges a worker until teardown; "
            "without elastic=True nothing ever revokes its work and the "
            "epoch join would deadlock — use elastic=True (or kinds "
            "'raise'/'exit')")

    trainer.record_training_start()
    trainer.failed_workers = []
    trainer.worker_failures = {}
    trainer.elastic_stats = {}
    x = np.asarray(dataset[trainer.features_col])
    y = np.asarray(dataset[trainer.label_col])
    if shuffle:
        perm = np.random.default_rng(trainer.seed).permutation(len(x))
        x, y = x[perm], y[perm]
    input_shape = x.shape[1:]
    params = trainer._initial_params(input_shape)
    blob = serialize_model(trainer.master_model, params)

    # reference parity (SURVEY §2.1 row 6): async trainers may run
    # parallelism_factor x num_workers concurrent tasks against the PS
    n = trainer.num_workers * getattr(trainer, "parallelism_factor", 1)
    ps_shards = int(getattr(trainer, "ps_shards", 1) or 1)
    recovery = bool(getattr(trainer, "recovery", False))
    # recovery routes through the ShardedServerGroup for ANY shard count
    # (the N=1 plan is the identity partition, bit-identical per
    # tests/test_ps_sharding.py) so there is exactly one supervised
    # lifecycle: servers held in a mutable list the supervisor can respawn
    # into.  recovery=False keeps the PR 2 paths untouched.
    sharded = ps_shards > 1 or recovery
    if sharded:
        # PS sharding (ps_sharding.py): partition the center weight vector
        # over N shard servers — each wraps the UNCHANGED per-algorithm
        # apply rule on its slice, with its own apply lock and update clock,
        # so staleness semantics are per-shard identical to the single-PS
        # path and PS CPU/NIC bandwidth scales with the shard count
        server = ShardedServerGroup(algorithm, blob, n, ps_shards)
        server.start()
    else:
        ps = allocate_parameter_server(algorithm, blob, n)
        server = SocketParameterServer(ps)
        server.start()
    supervisor = None
    if recovery:
        # PS resilience (resilience.py): periodic per-shard snapshots +
        # heartbeat-driven respawn-from-snapshot on the same address.  The
        # workers below reconnect-resume under a RetryPolicy; windows
        # committed after a shard's last snapshot are dropped (bounded
        # loss, same class as worker staleness).
        from .resilience import ShardSupervisor
        supervisor = ShardSupervisor(server, algorithm, n)
        supervisor.start()
    trainer._ps_supervisor = supervisor  # observability (tests/bench)

    # deal rows round-robin per worker (Spark round-robin repartition
    # analogue): every row lands on exactly one worker, nothing dropped;
    # shard sizes differ by at most one row and the workers' own
    # window-padding absorbs the raggedness (one shared compilation)
    if len(x) < n:
        raise ValueError(
            f"dataset of {len(x)} rows has fewer rows than workers ({n})")
    xs = [x[i::n] for i in range(n)]
    ys = [y[i::n] for i in range(n)]

    worker_cls = WORKER_CLASSES[algorithm]
    kw = _worker_kwargs(trainer, n, len(x))
    kw.update(worker_optimizer=trainer.worker_optimizer,
              ps_host="127.0.0.1",
              ps_port=(server.ports[0] if sharded else server.port))
    if sharded:
        # workers scatter-commit / gather-pull through a ShardedPSClient
        # (one socket + one receive-buffer pool per shard).  _shard_addr_hook
        # lets chaos tests interpose a networking.ChaosProxy per shard — the
        # workers then drive the real socket stack through the proxy while
        # the supervisor heartbeats the shards directly.
        addrs = server.addrs
        hook = getattr(trainer, "_shard_addr_hook", None)
        if hook is not None:
            addrs = [(str(h), int(p)) for h, p in hook(list(addrs))]
        kw.update(shard_plan=server.plan, shard_addrs=addrs)
    if recovery:
        kw.update(recovery=True,
                  retry_policy=getattr(trainer, "recovery_policy", None))

    if elastic:
        # elastic workers (resilience.py): lease-based shard redistribution,
        # death-respawn, and straggler stealing replace the static
        # round-robin deal + epoch-wave joins below
        try:
            workers = _run_elastic_host_ps(trainer, x, y, n, worker_cls,
                                           blob, kw)
        finally:
            if supervisor is not None:
                supervisor.stop()
            server.stop()
        trainer.history.clear()
        for w in workers:
            trainer.history.extend(w.history)
        fitted = server.get_model()
        trainer._fitted = fitted
        trainer.record_training_stop()
        return fitted

    workers = [worker_cls(blob, **kw) for _ in range(n)]
    share_compiled_state(workers)  # compile the window program once, not N×
    trainer._ps_workers = workers  # observability: transport counters (bench)

    ckpt = None
    start_epoch = 0
    states: List[Any] = [None] * n

    def full_state():
        """The complete async-training state as one host pytree.  Sharded
        runs store the GATHERED center plus the per-shard clock vector, so
        the checkpoint layout is shard-count-explicit (resume validates it
        against this run's ps_shards via the meta)."""
        if sharded:
            center, clocks = server.snapshot()
            clock = np.asarray(clocks, np.int64)
        else:
            with ps._lock:
                center = [w.copy() for w in ps.center]
                clock = np.int64(ps.num_updates)
        return {"center": center, "clock": clock,
                "workers": [jax.tree_util.tree_map(np.asarray, s)
                            for s in states]}

    try:
        if trainer.checkpoint_dir is not None:
            from .checkpoint import foreign_checkpoints, make_checkpointer
            backend = trainer.checkpoint_backend
            ckpt = make_checkpointer(trainer.checkpoint_dir, backend)
            latest = ckpt.latest_step()
            if resume and latest is None:
                foreign = foreign_checkpoints(trainer.checkpoint_dir, backend)
                if foreign:
                    raise ValueError(
                        f"resume=True with checkpoint_backend={backend!r}, "
                        f"but {trainer.checkpoint_dir} holds steps {foreign} "
                        "written by the other backend — resuming now would "
                        "silently retrain from scratch; use the backend that "
                        "wrote the checkpoints")
            if resume and latest is not None:
                # legacy pre-meta checkpoints were all spmd saves (host_ps
                # checkpointing used to raise NotImplementedError)
                meta = ckpt.read_meta(latest)
                if meta.get("engine", "spmd") != "host_ps":
                    raise ValueError(
                        f"checkpoint at {trainer.checkpoint_dir} was saved "
                        f"by engine={meta.get('engine', 'spmd')!r}; this "
                        "trainer is host_ps — resume with the same "
                        "configuration")
                if int(meta.get("ps_shards", 1)) != ps_shards:
                    raise ValueError(
                        f"checkpoint was saved with ps_shards="
                        f"{meta.get('ps_shards', 1)}; this trainer has "
                        f"ps_shards={ps_shards} — resume with the same "
                        "configuration")
                # template with the right pytree structure, then refill
                head = workers[0]
                p0 = head._weights_to_params(
                    server.snapshot()[0] if sharded else ps.center)
                states = [(p0, head._tx.init(p0)) for _ in range(n)]
                restored = ckpt.restore(full_state(), latest)
                if sharded:
                    server.restore_state(restored["center"],
                                         restored["clock"])
                else:
                    with ps._lock:
                        ps.center = [np.asarray(w, np.float32)
                                     for w in restored["center"]]
                        ps.num_updates = int(restored["clock"])
                states = [tuple(s) for s in restored["workers"]]
                start_epoch = latest

        # Without checkpointing there is no reason to barrier between
        # epochs: each worker runs all its epochs in one fully-async wave
        # (one connect, no stragglers at epoch joins) — the reference
        # execution model.  With a checkpoint_dir, epochs run as waves and
        # the joined state is saved.
        if ckpt is None:
            waves = [None]  # one wave, all epochs (worker default)
        else:
            waves = [(e, e + 1)
                     for e in range(start_epoch, trainer.num_epoch)]

        alive = [True] * n
        for epoch_range in waves:
            results: List[Optional[dict]] = [None] * n
            errors: List[tuple] = []

            def run(i, epoch_range=epoch_range):
                try:
                    results[i] = workers[i].train(
                        i,
                        {trainer.features_col: xs[i],
                         trainer.label_col: ys[i]},
                        initial_state=states[i],
                        epoch_range=epoch_range)
                except BaseException as e:  # propagate to the driver thread
                    errors.append((i, e))

            threads = [threading.Thread(target=run, args=(i,),
                                        name=f"dkt-worker-{i}")
                       for i in range(n) if alive[i]]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                # a dead SHARD is not a dead worker: it holds a partition of
                # the center that no survivor can reconstruct, so degraded
                # completion is impossible — surface it clearly regardless
                # of fault_tolerance
                shard_err = next((e for _, e in errors
                                  if isinstance(e, PSShardDown)), None)
                if shard_err is not None:
                    raise shard_err
                if not getattr(trainer, "fault_tolerance", False):
                    err = errors[0][1]
                    if isinstance(err, SystemExit):
                        # an 'exit'-faulted worker thread must surface as a
                        # training error, not exit the driver process
                        raise RuntimeError(
                            f"worker {errors[0][0]} exited: {err}") from err
                    raise err
                # degraded completion (SURVEY §5 fault table: reference
                # relied on Spark retry; we continue with survivors — the
                # center keeps every commit applied before the death).  A
                # tolerated death must stay diagnosable: keep the traceback
                # text on the trainer and say so on stderr.
                import sys
                import traceback
                for i, e in errors:
                    alive[i] = False
                    if i not in trainer.failed_workers:
                        trainer.failed_workers.append(i)
                        trainer.worker_failures[i] = "".join(
                            traceback.format_exception(e)).strip()
                    print(f"[distkeras_tpu] worker {i} died ({e!r}); "
                          "fault_tolerance: continuing with survivors",
                          file=sys.stderr)
                if not any(alive):
                    raise RuntimeError(
                        f"all {n} workers failed (fault_tolerance can "
                        "survive some, not all)") from errors[0][1]
            states = [r["state"] if r is not None else states[i]
                      for i, r in enumerate(results)]
            if ckpt is not None and (
                    epoch_range[1] % trainer.checkpoint_every == 0):
                ckpt.save(epoch_range[1], full_state(),
                          meta={"engine": "host_ps", "unit": "epoch",
                                "ps_shards": ps_shards})
    finally:
        if supervisor is not None:
            # stop the supervisor FIRST: the group teardown below must not
            # read as N shard deaths and trigger a respawn storm
            supervisor.stop()
        server.stop()
        if ckpt is not None:
            # durable async (orbax) saves + release the manager's
            # background threads — one leaks per train() otherwise
            ckpt.close()

    trainer.history.clear()
    for w in workers:
        trainer.history.extend(w.history)
    fitted = server.get_model()
    trainer._fitted = fitted
    trainer.record_training_stop()
    return fitted


def _run_elastic_host_ps(trainer, x, y, n: int, worker_cls, blob: dict,
                         kw: dict):
    """The elastic worker engine (``elastic=True`` — resilience.py).

    Replaces the static round-robin shard deal with a per-epoch
    ``LeaseLedger``: the epoch's rows are globally shuffled (deterministic
    in seed+epoch) and tiled into window-aligned leases that the worker
    threads acquire/renew/complete; a ``WorkerSupervisor`` revokes the
    leases of dead or wedged workers (survivors steal them) and respawns
    replacements under fresh ids from a live center pull.  After every
    epoch the ledger's exactly-once contract is asserted: killing k of N
    workers mid-epoch loses zero training examples.

    Returns the full worker list (original ids + respawns, id order) for
    history collection; resilience observability lands on the trainer as
    ``elastic_stats`` / ``failed_workers`` / ``worker_failures`` and
    ``_worker_supervisor``.
    """
    from .resilience import LeaseLedger, WorkerSupervisor

    win_rows = trainer.communication_window * trainer.batch_size
    total_windows = -(-len(x) // win_rows)
    lease_windows = getattr(trainer, "lease_windows", None)
    if lease_windows is None:
        # ~4 leases per worker per epoch: enough granularity for stealing
        # and respawn pickup without drowning in ledger round trips
        lease_windows = max(1, total_windows // (4 * n))
    head = worker_cls(blob, **kw)
    # compile the shared window program before the ledger clock starts (the
    # first lease's deadline must not pay the jit compile) and seed the
    # cold-start window estimate with the measured time: × n because the
    # real windows run under n-way thread contention.  The estimate is
    # generous by construction; each worker's EWMA tightens it from its
    # first renewal on.
    t_window = head.compile_windows(x, y)
    ledger = LeaseLedger(len(x), win_rows, lease_windows,
                         min_deadline=getattr(trainer, "lease_timeout", 5.0),
                         default_window_s=t_window * n)

    def factory(wid: int):
        w = head if wid == 0 else worker_cls(blob, **kw)
        share_compiled_state([head, w])  # one window program for everyone
        return w

    epoch_data: Dict[str, np.ndarray] = {}

    def run_fn(wid: int, worker):
        xe, ye = epoch_data["x"], epoch_data["y"]

        def data_fn(lease):
            return xe[lease.start:lease.stop], ye[lease.start:lease.stop]

        res = worker.train_leases(wid, ledger, data_fn,
                                  initial_state=sup.states.get(wid))
        sup.states[wid] = res["state"]
        return res

    sup = WorkerSupervisor(ledger, factory, run_fn, n)
    trainer._worker_supervisor = sup  # observability (tests/bench)
    epoch_reports = {}
    try:
        for epoch in range(trainer.num_epoch):
            # global per-epoch shuffle: leases are contiguous row ranges of
            # this permutation, so lease boundaries resample every epoch
            perm = np.random.default_rng(
                trainer.seed + 7919 * epoch).permutation(len(x))
            epoch_data["x"], epoch_data["y"] = x[perm], y[perm]
            sup.run_epoch(epoch)
            # the zero-data-loss contract, asserted per epoch
            epoch_reports[epoch] = ledger.assert_epoch_complete(epoch)
    finally:
        sup.shutdown()  # release 'hang'-faulted threads, join stragglers
        trainer.failed_workers = sorted(sup.failures)
        trainer.worker_failures = dict(sup.failures)
        trainer.elastic_stats = {
            "respawns": sup.respawns,
            "respawn_records": list(sup.respawn_records),
            "leases_reassigned": ledger.reassigned,
            "windows_per_worker": dict(ledger.windows_by_worker),
            "lease_completions": epoch_reports,
            "events": list(sup.events),
        }
        workers = [sup.workers[wid] for wid in sorted(sup.workers)]
        trainer._ps_workers = workers
    return workers


def _worker_kwargs(trainer, n: int, rows: int) -> dict:
    """Worker construction kwargs shared by the host (thread) and process
    PS engines — one place for the LR-schedule horizon formula and the
    elastic rho special-case.

    Schedule horizon per worker: the largest shard has ceil(rows/n) rows →
    windows/epoch × window mini-steps × epochs, ceil-divided by the
    accumulation factor (workers differ by at most one window).
    """
    accum = getattr(trainer, "gradient_accumulation", 1)
    win = trainer.communication_window
    shard_rows = -(-rows // n)
    windows_pe = -(-shard_rows // (win * trainer.batch_size))
    kw = dict(
        loss=trainer.loss, communication_window=win,
        features_col=trainer.features_col, label_col=trainer.label_col,
        batch_size=trainer.batch_size, num_epoch=trainer.num_epoch,
        learning_rate=trainer.learning_rate, seed=trainer.seed,
        lr_schedule=getattr(trainer, "lr_schedule", None),
        schedule_steps=-(-windows_pe * win * trainer.num_epoch // accum),
        gradient_accumulation=accum,
        gradient_clip_norm=getattr(trainer, "gradient_clip_norm", None),
        wire_dtype=getattr(trainer, "wire_dtype", None),
        wire_topk=getattr(trainer, "wire_topk", 0.01),
        wire_topk_dtype=getattr(trainer, "wire_topk_dtype", None),
        comm_overlap=getattr(trainer, "comm_overlap", False),
        fault_injection=getattr(trainer, "fault_injection", None))
    if trainer.ALGORITHM in ("aeasgd", "eamsgd"):
        kw["rho"] = getattr(trainer, "rho", 5.0)
    return kw


def run_process_ps_training(trainer, dataset, shuffle: bool = False
                            ) -> FittedModel:
    """Execute a DistributedTrainer with workers as separate OS PROCESSES.

    This is the actual reference topology (SURVEY.md §3.1): the driver
    process hosts the socket PS; each worker is its own interpreter,
    launched with ``job_deployment.LocalJobRunner`` on loopback.  Unlike
    ``execution='host_ps'`` (threads in one interpreter, GIL-shared), the
    workers here share nothing but the TCP socket: the test proof that the
    wire protocol, and not thread memory sharing, carries training.

    Workers are launched *uncoordinated* (``Job(coordinated=False)``): PS
    clients never use collectives, and a shared ``jax.distributed`` group
    would stall the healthy workers at the init barrier if one died.

    Model blob and per-worker shards travel via a driver-local scratch
    directory (the Spark analogue: closure + partition shipping);
    histories return the same way.  A real multi-host DCN deployment keeps
    the same ``ps_worker_main`` entry point and ``DISTKERAS_TPU_*`` env
    contract via ``SSHJobRunner``, but additionally needs a shared scratch
    path and a PS bound on a routable interface — same-host processes are
    what this function wires up today.  Checkpoint/resume stays on the
    in-process engines.
    """
    import json
    import tempfile

    from .job_deployment import Job, LocalJobRunner
    from .ps_worker_main import save_model_blob

    algorithm = trainer.ALGORITHM
    if algorithm not in WORKER_CLASSES:
        raise ValueError(
            f"execution='process_ps' supports PS algorithms "
            f"{sorted(WORKER_CLASSES)}, not {algorithm!r} "
            f"({type(trainer).__name__})")
    if trainer.checkpoint_dir is not None:
        raise ValueError(
            "checkpoint/resume is not supported on execution='process_ps' "
            "(use 'host_ps' for epoch-wave checkpoints)")
    from .workers import parse_fault_injection
    if any(k == "hang" for k, _ in parse_fault_injection(
            getattr(trainer, "fault_injection", None)).values()):
        raise ValueError(
            "fault_injection kind 'hang' wedges a worker process forever; "
            "the process engine has no lease ledger to revoke its work — "
            "use execution='host_ps' with elastic=True")

    trainer.record_training_start()
    trainer.failed_workers = []
    trainer.worker_failures = {}
    x = np.asarray(dataset[trainer.features_col])
    y = np.asarray(dataset[trainer.label_col])
    if shuffle:
        perm = np.random.default_rng(trainer.seed).permutation(len(x))
        x, y = x[perm], y[perm]
    params = trainer._initial_params(x.shape[1:])
    blob = serialize_model(trainer.master_model, params)

    n = trainer.num_workers * getattr(trainer, "parallelism_factor", 1)
    if len(x) < n:
        raise ValueError(
            f"dataset of {len(x)} rows has fewer rows than workers ({n})")
    # all validation/config prep BEFORE the server starts: an error here
    # must not leak the listener thread
    optimizer = trainer.worker_optimizer
    if not isinstance(optimizer, str):  # Optimizer object → JSON config
        optimizer = optimizer.get_config()
    kw = _worker_kwargs(trainer, n, len(x))
    if callable(kw["lr_schedule"]):
        raise ValueError(
            "execution='process_ps' cannot ship a callable lr_schedule to "
            "worker processes — pass a name or config dict "
            "(e.g. 'warmup_cosine'), or use execution='host_ps'")

    ps = allocate_parameter_server(algorithm, blob, n)
    server = SocketParameterServer(ps)
    server.start()
    try:
        with tempfile.TemporaryDirectory(prefix="dkt_procps_") as tmp:
            model_path = os.path.join(tmp, "model.npz")
            save_model_blob(model_path, blob)
            shard_paths, result_paths = [], []
            for i in range(n):  # round-robin deal, as the thread engine
                p = os.path.join(tmp, f"shard_{i}.npz")
                np.savez(p, x=x[i::n], y=y[i::n])
                shard_paths.append(p)
                result_paths.append(os.path.join(tmp, f"result_{i}.npz"))
            cfg_path = os.path.join(tmp, "worker_config.json")
            with open(cfg_path, "w") as f:
                json.dump({
                    **kw,
                    "algorithm": algorithm,
                    "model_path": model_path,
                    "shard_paths": shard_paths,
                    "result_paths": result_paths,
                    "ps_host": "127.0.0.1",
                    "ps_port": server.port,
                    "worker_optimizer": optimizer,
                }, f)

            # repo root on PYTHONPATH so `-m distkeras_tpu.ps_worker_main`
            # resolves in the child even without an installed package
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env = {"PYTHONPATH": os.pathsep.join(
                p for p in (pkg_root, os.environ.get("PYTHONPATH")) if p)}
            job = Job(name=f"{algorithm}-process-ps", script="-m",
                      args=["distkeras_tpu.ps_worker_main", cfg_path],
                      hosts=["127.0.0.1"] * n, env=env, coordinated=False)
            job.run(LocalJobRunner())
            # max() would mask signal deaths (negative codes) behind a 0
            failed = [i for i, c in enumerate(job.returncodes) if c != 0]
            if failed:
                if not getattr(trainer, "fault_tolerance", False):
                    raise RuntimeError(
                        f"worker process failed (exit codes "
                        f"{job.returncodes})")
                if len(failed) == n:
                    raise RuntimeError(
                        f"all {n} worker processes failed (exit codes "
                        f"{job.returncodes}); fault_tolerance can survive "
                        "some, not all")
                # degraded completion: the PS already holds every commit
                # the dead workers applied before dying (their EOF was a
                # normal disconnect to the server).  Keep the exit codes
                # diagnosable and say so on stderr.
                import sys
                trainer.failed_workers = failed
                for i in failed:
                    trainer.worker_failures[i] = (
                        f"exit code {job.returncodes[i]}")
                print(f"[distkeras_tpu] worker processes {failed} exited "
                      f"nonzero ({job.returncodes}); fault_tolerance: "
                      "continuing with survivors", file=sys.stderr)

            trainer.history.clear()
            for i, p in enumerate(result_paths):
                if i in failed:
                    continue  # no result file from a dead worker
                with np.load(p) as z:
                    trainer.history.extend(z["history"].tolist())
    finally:
        server.stop()

    fitted = server.get_model()
    trainer._fitted = fitted
    trainer.record_training_stop()
    return fitted
