"""Trainer hierarchy — the public API of the framework.

API parity with the reference trainer set (reference:
``distkeras/trainers.py`` — SURVEY.md §2.1 rows 1–11): ``SingleTrainer``,
``AveragingTrainer``, ``EnsembleTrainer``, and the parameter-server algorithms
``DOWNPOUR``, ``ADAG``, ``AEASGD``, ``EAMSGD``, ``DynSGD``.  Constructor kwargs
match the reference spellings (``keras_model``, ``worker_optimizer``, ``loss``,
``num_workers``, ``batch_size``, ``features_col``, ``label_col``,
``num_epoch``, ``communication_window``, ``rho``, ``momentum``, ...), and
``train(dataset) -> FittedModel`` plus ``get_training_time()`` behave the same.

Execution is entirely different (that's the point): instead of shipping a
pickled worker closure to Spark executors and exchanging deltas with a socket
PS (reference ``DistributedTrainer.train`` → ``rdd.mapPartitionsWithIndex``),
training compiles into a single SPMD XLA program per epoch over a TPU device
mesh (see ``parallel/spmd.py``).  The async algorithms keep their update rules
with commits executing in deterministic bulk-synchronous rounds; the
semantically-exact threaded-async path is available with
``execution='host_ps'`` (see ``parameter_servers.py``).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .core.model import Sequential, FittedModel, serialize_model
from .core import optimizers as opt_lib
from .core.train import (batch_epoch_arrays, init_state,
                         make_epoch_runner, make_packed_epoch_runner)
from .data.dataset import Dataset
from .parallel import mesh as mesh_lib
from .parallel.spmd import SPMDEngine, DistState, shape_epoch_data
from .parallel import rules

tmap = jax.tree_util.tree_map


def _as_model(keras_model) -> Sequential:
    """Accept a native Sequential or a Keras model (converted via adapter)."""
    if isinstance(keras_model, Sequential):
        return keras_model
    if isinstance(keras_model, FittedModel):
        return keras_model.model
    try:
        from .core.keras_adapter import convert_keras_model
        return convert_keras_model(keras_model)
    except ImportError:  # pragma: no cover
        raise TypeError(f"Cannot interpret model {type(keras_model)}")


def _require_masked_loss(loss):
    """The one segment_col loss rule (SingleTrainer + DistributedTrainer):
    packed labels carry -1 sentinels, which a plain sparse CE would clamp
    to class 0 and silently train document boundaries wrong."""
    if isinstance(loss, str) and "masked" not in loss:
        raise ValueError(
            f"segment_col needs a *_masked loss (packed labels mark "
            f"cross-document/padding positions -1), got {loss!r} — use "
            "e.g. 'sparse_categorical_crossentropy_masked_from_logits'")


class Trainer:
    """Abstract base (reference: ``trainers.py :: Trainer``).

    Holds the model spec + loss + worker optimizer and the wall-clock
    bookkeeping (``record_training_start/stop``, ``get_training_time``).
    """

    def __init__(self, keras_model, loss: str = "categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate: Optional[float] = None,
                 seed: int = 0, lr_schedule=None,
                 gradient_accumulation: int = 1,
                 gradient_clip_norm: Optional[float] = None,
                 early_stopping_patience: Optional[int] = None,
                 early_stopping_min_delta: float = 0.0):
        self.master_model = _as_model(keras_model)
        self.loss = loss
        self.worker_optimizer = worker_optimizer
        self.learning_rate = learning_rate
        # modernized worker-optimizer surface (no reference counterpart —
        # the 2016 upstream is fixed-LR): ``lr_schedule`` is a name/dict/
        # callable resolved by ``core.optimizers.get_schedule`` against the
        # trainer's own total-update count; ``gradient_accumulation`` = K
        # averages K mini-step gradients per optimizer update
        self.lr_schedule = lr_schedule
        self.gradient_accumulation = int(gradient_accumulation)
        if self.gradient_accumulation < 1:
            raise ValueError("gradient_accumulation must be >= 1")
        self.gradient_clip_norm = (float(gradient_clip_norm)
                                   if gradient_clip_norm is not None
                                   else None)
        if self.gradient_clip_norm is not None \
                and self.gradient_clip_norm <= 0:
            raise ValueError("gradient_clip_norm must be > 0")
        # early stopping on validation loss (train(validation_data=...)):
        # stop after `patience` epochs without > min_delta improvement
        self.early_stopping_patience = (
            int(early_stopping_patience)
            if early_stopping_patience is not None else None)
        if self.early_stopping_patience is not None \
                and self.early_stopping_patience < 1:
            raise ValueError("early_stopping_patience must be >= 1")
        self.early_stopping_min_delta = float(early_stopping_min_delta)
        self.validation_history: List[float] = []
        self.stopped_epoch: Optional[int] = None
        self.seed = seed
        self.history: List[float] = []
        self.metrics: List[dict] = []
        self.training_time = 0.0
        self._time_start: Optional[float] = None
        self._fitted: Optional[FittedModel] = None
        if isinstance(keras_model, FittedModel):
            self._initial_weights = keras_model.get_weights()
        else:
            self._initial_weights = None

    # -- timing (exact parity with reference Trainer) ------------------------
    def record_training_start(self):
        self.training_time = 0.0
        self._time_start = time.time()

    def record_training_stop(self):
        assert self._time_start is not None
        self.training_time = time.time() - self._time_start

    def get_training_time(self) -> float:
        return self.training_time

    def get_history(self) -> List[float]:
        return self.history

    # -- model plumbing ------------------------------------------------------
    def _initial_params(self, input_shape):
        params = self.master_model.init(jax.random.PRNGKey(self.seed),
                                        input_shape)
        if self._initial_weights is not None:
            params = self.master_model.set_weights(params,
                                                   self._initial_weights)
        return params

    def serialize(self) -> dict:
        """Serialized master model (reference: ``Trainer.serialize``)."""
        if self._fitted is not None:
            return self._fitted.serialize()
        raise ValueError("Trainer has no fitted model yet; call train() first")

    def train(self, dataset: Dataset, shuffle: bool = False) -> FittedModel:
        raise NotImplementedError

    # -- validation / early stopping (beyond-reference: upstream trains
    # -- blind — SURVEY.md §5 has no observability beyond loss lists) ------
    def _setup_validation(self, validation_data: Optional[Dataset]):
        if validation_data is None:
            if self.early_stopping_patience is not None:
                raise ValueError(
                    "early_stopping_patience needs validation_data passed "
                    "to train()")
            return None
        from .core.losses import get_loss
        xv = jnp.asarray(validation_data[self.features_col])
        yv = jnp.asarray(validation_data[self.label_col])
        loss_fn = get_loss(self.loss)
        model = self.master_model
        # packed validation (round-4 VERDICT weak #4): thread the segment ids
        # through the forward so attention keeps its document isolation; the
        # *_masked loss (enforced at train() entry) then drops the label -1
        # cross-document/padding positions, exactly as in training
        seg_col = getattr(self, "segment_col", None)
        if seg_col is not None and seg_col not in validation_data:
            raise ValueError(
                f"validation_data lacks the segment column {seg_col!r} — "
                "pack it the same way as the training corpus "
                "(data/packing.py)")
        sv = (jnp.asarray(validation_data[seg_col])
              if seg_col is not None else None)

        @jax.jit
        def val_loss(params):
            pred = model.apply(params, xv, train=False, segment_ids=sv)
            return loss_fn(yv, pred)

        self.validation_history = []
        self._val_best = float("inf")
        self._val_bad = 0
        return val_loss

    def _validate_epoch(self, val_fn, params, epoch: int, metrics=None
                        ) -> bool:
        """Record this epoch's validation loss; True → stop now (no
        improvement > min_delta for ``early_stopping_patience`` epochs)."""
        vl = float(val_fn(params))
        self.validation_history.append(vl)
        if metrics is not None:
            metrics.logger.log(kind="val", epoch=epoch, val_loss=vl)
        patience = self.early_stopping_patience
        if patience is None:
            return False
        if vl < self._val_best - self.early_stopping_min_delta:
            self._val_best = vl
            self._val_bad = 0
            return False
        self._val_bad += 1
        if self._val_bad >= patience:
            self.stopped_epoch = epoch
            return True
        return False


class SingleTrainer(Trainer):
    """Single-device baseline (reference: ``trainers.py :: SingleTrainer`` —
    coalesce to one partition, one SequentialWorker).  Here: one chip, the
    whole epoch as one jitted ``lax.scan`` over minibatches."""

    def __init__(self, keras_model, features_col: str = "features",
                 label_col: str = "label", batch_size: int = 32,
                 num_epoch: int = 1, loss: str = "categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate=None, seed: int = 0,
                 lr_schedule=None, gradient_accumulation: int = 1,
                 gradient_clip_norm: Optional[float] = None,
                 early_stopping_patience: Optional[int] = None,
                 early_stopping_min_delta: float = 0.0,
                 segment_col: Optional[str] = None):
        super().__init__(keras_model, loss, worker_optimizer, learning_rate,
                         seed, lr_schedule, gradient_accumulation,
                         gradient_clip_norm,
                         early_stopping_patience, early_stopping_min_delta)
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = int(batch_size)
        self.num_epoch = int(num_epoch)
        # sequence packing (data/packing.py): name of the segment-ids
        # column; attention isolates documents and the loss should be a
        # *_masked variant so cross-document label -1 positions drop out
        self.segment_col = segment_col

    def train(self, dataset: Dataset, shuffle: bool = False,
              validation_data: Optional[Dataset] = None) -> FittedModel:
        if self.segment_col is not None:
            _require_masked_loss(self.loss)
        self.record_training_start()
        x = dataset[self.features_col]
        y = dataset[self.label_col]
        input_shape = x.shape[1:]
        params = self._initial_params(input_shape)
        # schedule horizon = optimizer updates over the whole run: ceil-div
        # mini-steps by the accumulation factor (MultiSteps advances its
        # inner clock once per K mini-steps)
        steps_per_epoch = -(-len(x) // self.batch_size)
        total_updates = -(-steps_per_epoch * self.num_epoch
                          // self.gradient_accumulation)
        state, tx = init_state(self.master_model, jax.random.PRNGKey(self.seed),
                               input_shape, self.worker_optimizer,
                               self.learning_rate, self.lr_schedule,
                               total_updates, self.gradient_accumulation,
                               self.gradient_clip_norm)
        state = state._replace(params=params)
        packed = self.segment_col is not None
        runner = (make_packed_epoch_runner(self.master_model, self.loss, tx)
                  if packed
                  else make_epoch_runner(self.master_model, self.loss, tx))
        cols = {"x": x, "y": y}
        if packed:
            cols["s"] = dataset[self.segment_col]
        rng = jax.random.PRNGKey(self.seed + 1)
        val_fn = self._setup_validation(validation_data)
        for epoch in range(self.num_epoch):
            ds = (Dataset(cols).shuffle(self.seed + epoch) if shuffle
                  else Dataset(cols))
            *stacked, mb, nb = batch_epoch_arrays(
                self.batch_size, *(np.asarray(ds[k]) for k in cols))
            rng, sub = jax.random.split(rng)
            state, losses = runner(state, *map(jnp.asarray, stacked),
                                   jnp.asarray(mb), sub)
            self.history.extend(np.asarray(losses).tolist())
            if val_fn is not None and self._validate_epoch(
                    val_fn, state.params, epoch):
                break
        self._fitted = FittedModel(self.master_model, state.params)
        self.record_training_stop()
        return self._fitted


class DistributedTrainer(Trainer):
    """Base for multi-worker trainers (reference:
    ``trainers.py :: DistributedTrainer``): owns worker count, batch/window
    config, and the train() lifecycle.  The reference's ``service()`` (PS
    thread startup) maps to mesh construction + engine build here."""

    ALGORITHM = "local"
    DEFAULT_WINDOW = 5

    def __init__(self, keras_model, num_workers: Optional[int] = None,
                 batch_size: int = 32, features_col: str = "features",
                 label_col: str = "label", num_epoch: int = 1,
                 communication_window: Optional[int] = None,
                 loss: str = "categorical_crossentropy",
                 worker_optimizer="sgd", learning_rate=None,
                 execution: str = "spmd", mesh=None, seed: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1,
                 checkpoint_unit: str = "epoch",
                 checkpoint_backend: str = "npz",
                 metrics_path: Optional[str] = None,
                 wire_dtype: Optional[str] = None,
                 wire_topk: float = 0.01,
                 wire_topk_dtype: Optional[str] = None,
                 lr_schedule=None, gradient_accumulation: int = 1,
                 gradient_clip_norm: Optional[float] = None,
                 early_stopping_patience: Optional[int] = None,
                 early_stopping_min_delta: float = 0.0,
                 fault_tolerance: bool = False,
                 fault_injection: Optional[dict] = None,
                 segment_col: Optional[str] = None):
        super().__init__(keras_model, loss, worker_optimizer, learning_rate,
                         seed, lr_schedule, gradient_accumulation,
                         gradient_clip_norm,
                         early_stopping_patience, early_stopping_min_delta)
        # sequence packing on the distributed engine (the SPMD twin of
        # SingleTrainer(segment_col=…)): name of the segment-ids column;
        # needs a *_masked loss, SPMD execution only
        self.segment_col = segment_col
        self.mesh = mesh if mesh is not None else mesh_lib.get_mesh(num_workers)
        self.num_workers = int(self.mesh.devices.size)
        self.batch_size = int(batch_size)
        self.features_col = features_col
        self.label_col = label_col
        self.num_epoch = int(num_epoch)
        self.communication_window = int(
            communication_window if communication_window is not None
            else self.DEFAULT_WINDOW)
        self.execution = execution
        # host_ps/process_ps wire compression for commits: "bfloat16" (2x
        # fewer delta bytes), "int8" (4x, per-tensor scales + error
        # feedback), or "topk" (sparse top-k selection: only the wire_topk
        # densest delta coordinates ship, ~1/density fewer bytes, with
        # error feedback; values optionally bf16/int8-coded on top via
        # wire_topk_dtype — workers.PSWorker.commit); the SPMD path has no
        # wire — deltas ride ICI inside the XLA program
        self.wire_dtype = wire_dtype
        self.wire_topk = float(wire_topk)
        self.wire_topk_dtype = wire_topk_dtype
        if wire_dtype == "topk":
            if not 0.0 < self.wire_topk <= 1.0:
                raise ValueError(
                    f"wire_topk must be a density in (0, 1], got "
                    f"{self.wire_topk}")
            if wire_topk_dtype not in (None, "bfloat16", "int8"):
                raise ValueError(
                    "wire_topk_dtype must be None, 'bfloat16' or 'int8', "
                    f"got {wire_topk_dtype!r}")
        elif wire_topk_dtype is not None:
            raise ValueError(
                "wire_topk_dtype applies to wire_dtype='topk' only")
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = max(int(checkpoint_every), 1)
        if checkpoint_unit not in ("epoch", "round"):
            raise ValueError("checkpoint_unit must be 'epoch' or 'round'")
        # 'round' = mid-epoch granularity on the SPMD engine: steps are the
        # global round clock (DistState.round_idx); 'epoch' keeps the whole
        # epoch as one XLA program (fastest) and checkpoints between epochs
        self.checkpoint_unit = checkpoint_unit
        if checkpoint_backend not in ("npz", "orbax"):
            raise ValueError("checkpoint_backend must be 'npz' or 'orbax'")
        self.checkpoint_backend = checkpoint_backend
        self.metrics_path = metrics_path
        # PS-engine fault story (SURVEY §5: the reference delegated worker
        # death to Spark task retry).  fault_tolerance=True: a dying
        # PS worker (thread exception / process exit) no longer aborts the
        # run — survivors finish, the center keeps every commit applied
        # before the death, and the dead ids land in ``failed_workers``.
        # fault_injection={worker_id: n}: that worker raises at its n+1-th
        # commit — the fault-injection hook the tests use.
        self.fault_tolerance = bool(fault_tolerance)
        self.fault_injection = fault_injection
        self.failed_workers: List[int] = []
        # worker id -> traceback text / exit code of tolerated deaths, so a
        # genuine bug surviving under fault_tolerance stays diagnosable
        self.worker_failures: dict = {}
        self._engine: Optional[SPMDEngine] = None
        self._state: Optional[DistState] = None

    # -- engine lifecycle (≈ reference service()/stop_service()) -------------
    def _elastic_alpha(self) -> Optional[float]:
        return None

    def service(self, input_shape) -> SPMDEngine:
        engine = SPMDEngine(
            self.master_model, self.loss, self.worker_optimizer, self.mesh,
            self.ALGORITHM, self.communication_window, self.learning_rate,
            alpha=self._elastic_alpha(), lr_schedule=self.lr_schedule,
            schedule_steps=getattr(self, "_schedule_steps", None),
            gradient_accumulation=self.gradient_accumulation,
            gradient_clip_norm=self.gradient_clip_norm,
            packed=self.segment_col is not None)
        self._state = engine.init_state(
            jax.random.PRNGKey(self.seed), self._input_shape,
            initial_params=self._initial_params(self._input_shape))
        return engine

    def train(self, dataset: Dataset, shuffle: bool = False,
              resume: bool = False,
              validation_data: Optional[Dataset] = None) -> FittedModel:
        if self.execution in ("host_ps", "process_ps") \
                and (validation_data is not None
                     or self.early_stopping_patience is not None):
            raise ValueError(
                "validation_data/early stopping run between SPMD epochs; "
                "the async PS engines have no between-epoch hook (workers "
                "own their epoch loops) — use execution='spmd'")
        if self.segment_col is not None:
            if self.execution != "spmd":
                raise ValueError(
                    "segment_col (packed training) runs on the SPMD "
                    "engine only — the PS workers don't thread segment "
                    "ids; use execution='spmd'")
            _require_masked_loss(self.loss)
        if getattr(self, "stream", False):
            # streaming online learning: dataset is a StreamSource; the
            # horizon loop owns shuffling (per-horizon, deterministic) and
            # there are no epoch waves to resume between
            if resume:
                raise ValueError(
                    "resume does not apply to stream=True (no epoch waves; "
                    "the PS center is the live state)")
            from .streaming import run_stream_training
            return run_stream_training(self, dataset)
        if self.execution == "host_ps":
            from .parameter_servers import run_host_ps_training
            return run_host_ps_training(self, dataset, shuffle, resume=resume)
        if self.execution == "process_ps":
            if resume:
                raise ValueError(
                    "resume is not supported on execution='process_ps'")
            from .parameter_servers import run_process_ps_training
            return run_process_ps_training(self, dataset, shuffle)
        if self.fault_tolerance or self.fault_injection:
            raise ValueError(
                "fault_tolerance/fault_injection apply to the PS engines "
                "(execution='host_ps'/'process_ps'); the SPMD program is "
                "bulk-synchronous — a lost participant is a lost collective, "
                "and its recovery story is checkpoint_dir + train("
                "resume=True)")
        self.record_training_start()
        # before any resource (checkpoint manager, metrics file) opens:
        # a bad validation config must not leak them
        val_fn = self._setup_validation(validation_data)
        x = np.asarray(dataset[self.features_col])
        y = np.asarray(dataset[self.label_col])
        seg = (np.asarray(dataset[self.segment_col])
               if self.segment_col is not None else None)
        self._input_shape = x.shape[1:]
        from .data.pipeline import num_rounds
        rpe = num_rounds(len(x), self.num_workers, self.communication_window,
                         self.batch_size)  # rounds per epoch (constant)
        # per-worker optimizer updates over the run (the LR-schedule horizon):
        # rounds × window mini-steps per epoch, ceil-divided by accumulation
        self._schedule_steps = -(-rpe * self.communication_window
                                 * self.num_epoch
                                 // self.gradient_accumulation)
        engine = self.service(self._input_shape)
        self._engine = engine
        ckpt = None
        start_epoch = 0
        skip_rounds = 0  # rounds of start_epoch already done (round unit)
        if resume and self.checkpoint_dir is None:
            raise ValueError("train(resume=True) needs checkpoint_dir")
        if self.checkpoint_dir is not None:
            from .checkpoint import foreign_checkpoints, make_checkpointer
            ckpt = make_checkpointer(self.checkpoint_dir,
                                     self.checkpoint_backend)
            latest = ckpt.latest_step()
            if resume and latest is None:
                foreign = foreign_checkpoints(self.checkpoint_dir,
                                              self.checkpoint_backend)
                if foreign:
                    raise ValueError(
                        f"resume=True with checkpoint_backend="
                        f"{self.checkpoint_backend!r}, but {self.checkpoint_dir}"
                        f" holds steps {foreign} written by the other backend"
                        " — resuming now would silently retrain from scratch;"
                        " use the backend that wrote the checkpoints")
            if resume and latest is not None:
                # a step number only means what the saving run meant by it:
                # refuse to reinterpret epoch-steps as rounds or vice versa.
                # Legacy pre-meta checkpoints were all spmd/epoch saves.
                meta = ckpt.read_meta(latest)
                saved_unit = meta.get("unit", "epoch")
                if meta.get("engine", "spmd") != "spmd" \
                        or saved_unit != self.checkpoint_unit:
                    raise ValueError(
                        f"checkpoint at {self.checkpoint_dir} was saved by "
                        f"engine={meta.get('engine', 'spmd')!r} with "
                        f"checkpoint_unit={saved_unit!r}; this trainer is "
                        f"spmd/{self.checkpoint_unit!r} — resume with the "
                        "same configuration")
                if self.checkpoint_unit == "round" and \
                        meta.get("rounds_per_epoch") not in (None, rpe):
                    raise ValueError(
                        f"checkpoint was saved with rounds_per_epoch="
                        f"{meta['rounds_per_epoch']} but this configuration "
                        f"gives {rpe} (batch_size/communication_window/"
                        "dataset size changed) — resume with the same "
                        "configuration")
                # live state as the restore target: npz reads only its
                # structure/shapes; orbax restores each host's shards in
                # place from the abstract (shape/dtype/sharding) view
                self._state = engine.put_state(
                    ckpt.restore(self._state, latest))
                if self.checkpoint_unit == "round":
                    # step k = global round clock after k rounds
                    start_epoch, skip_rounds = divmod(latest, rpe)
                else:
                    # step k = state after k epochs
                    start_epoch = latest
        from .metrics import EpochMetrics, MetricsLogger
        metrics = EpochMetrics(MetricsLogger(self.metrics_path),
                               num_chips=self.num_workers)
        self.metrics = metrics.logger.events
        rngs = engine.worker_rngs(self.seed + 17)
        try:
            for epoch in range(start_epoch, self.num_epoch):
                t0 = time.time()
                if shuffle:
                    # deterministic per-epoch reshuffle (reference shuffles
                    # once up front via utils.shuffle; per-epoch is strictly
                    # better for convergence and still seed-reproducible)
                    perm = np.random.default_rng(
                        self.seed + epoch).permutation(len(x))
                    xe, ye = x[perm], y[perm]
                    se = seg[perm] if seg is not None else None
                else:
                    xe, ye, se = x, y, seg
                shaped = shape_epoch_data(
                    xe, ye, self.num_workers, self.communication_window,
                    self.batch_size, columns_seg=se)
                if se is not None:
                    xb, yb, sb, mb, rounds = shaped
                else:
                    (xb, yb, mb, rounds), sb = shaped, None
                first = skip_rounds if epoch == start_epoch else 0
                if self.checkpoint_unit == "round" and ckpt is not None:
                    # per-round stepping: same round program as the epoch
                    # scan (bit-identical), checkpointable mid-epoch on the
                    # global round clock.  Losses stay on device until the
                    # epoch ends so rounds without a checkpoint dispatch
                    # without a host sync.
                    losses = []
                    done = int(self._state.round_idx)
                    for r in range(first, rounds):
                        self._state, loss = engine.run_round(
                            self._state, xb[r], yb[r], mb[r], rngs,
                            s=sb[r] if sb is not None else None)
                        losses.append(loss)
                        done += 1
                        if done % self.checkpoint_every == 0:
                            # live (possibly sharded) state: npz device_gets
                            # internally; orbax snapshots to host in save()
                            # and writes async — per-host shards on a pod
                            ckpt.save(done, self._state,
                                      meta={"engine": "spmd",
                                            "unit": "round",
                                            "rounds_per_epoch": rpe})
                    losses = (np.asarray(jax.device_get(jnp.stack(losses)),
                                         np.float32)
                              if losses else np.zeros((0,), np.float32))
                else:
                    self._state, losses = engine.run_epoch(
                        self._state, xb, yb, mb, rngs, sb=sb)
                    losses = np.asarray(losses)
                self.history.extend(losses.tolist())
                # every real row trains exactly once (tail is padded+masked,
                # not dropped); a resumed partial epoch counts exactly the
                # real rows of its remaining rounds (mask sum)
                examples = (len(xe) if first == 0
                            else int(mb[first:].sum()))
                metrics.epoch(epoch, examples, time.time() - t0,
                              float(losses.mean()) if len(losses) else 0.0)
                if (ckpt is not None and self.checkpoint_unit == "epoch"
                        and (epoch + 1) % self.checkpoint_every == 0):
                    ckpt.save(epoch + 1, self._state,
                              meta={"engine": "spmd", "unit": "epoch"})
                if val_fn is not None and self._validate_epoch(
                        val_fn, self._state.center, epoch, metrics):
                    break
        finally:
            metrics.logger.close()
            if ckpt is not None:
                # durable async (orbax) saves + release the manager's
                # background threads — one leaks per train() otherwise
                ckpt.close()
        center = jax.device_get(self._state.center)
        self._fitted = FittedModel(self.master_model, center)
        self.record_training_stop()
        return self._fitted


class AsynchronousDistributedTrainer(DistributedTrainer):
    """Async-family base (reference: same-named class). On the SPMD engine the
    async commits execute as deterministic rounds; semantics notes in
    ``parallel/spmd.py``.

    ``parallelism_factor`` (reference parity, SURVEY §2.1 row 6): async
    trainers may run more concurrent worker tasks than executors — the
    reference repartitions to ``parallelism_factor * num_workers`` Spark
    tasks.  Honored on ``execution='host_ps'`` (that many true-async worker
    threads share the PS).  The SPMD engine is bulk-synchronous with exactly
    one worker per chip, so a factor > 1 is rejected there rather than
    silently ignored.

    ``comm_overlap`` (PS engines only): pipeline the worker↔PS transport —
    every communication window becomes ONE combined 'u' (commit+pull) round
    trip whose reply is received while the next window's jitted compute
    runs, so the DCN latency hides behind the device instead of idling it.
    The center each window trains against is one window stale.  ``None``
    (default) resolves per algorithm: ON for the delta family
    (DOWNPOUR/ADAG/DynSGD — staleness-tolerant by construction, Dean et
    al. 2012), OFF for the elastic family (its force term prefers a fresh
    center; pass ``comm_overlap=True`` to trade one window of center
    staleness for the hidden round trip).  The SPMD engine has no wire to
    overlap, so an explicit setting there is rejected.

    ``ps_shards`` (``execution='host_ps'`` only): partition the center
    weight vector across N parameter-server shard processes
    (``ps_sharding.py`` — greedy bin-packing by byte size, oversized
    tensors split row-wise), so PS-side CPU and NIC bandwidth scale with
    the shard count instead of capping async throughput at one server.
    Each shard wraps the unchanged per-algorithm apply rule on its slice
    with its own clock, so staleness semantics are per-shard identical to
    the single-PS path, and ``ps_shards=1`` (default) is today's
    single-server behavior bit for bit.  See docs/host_ps.md.

    ``elastic`` (``execution='host_ps'`` only): make the *workers*
    survivable too (``resilience.LeaseLedger``/``WorkerSupervisor``).  Each
    epoch's data is partitioned into window-aligned **leases** (of
    ``lease_windows`` communication windows each; default ≈ 4 leases per
    worker per epoch) that workers acquire, renew once per committed window
    (the heartbeat rides the commit cadence), and complete.  A worker that
    dies (raise / exit) has its unfinished leases revoked and a replacement
    respawned under a fresh id from a live center pull; one that wedges
    past its lease deadline (per-worker window-rate EWMA × slack, floored
    by ``lease_timeout`` seconds) has its leases stolen by surviving
    workers — straggler mitigation.  Contract: every lease is completed
    exactly once per epoch by someone, so killing k of N workers mid-epoch
    loses **zero** training examples (asserted after each epoch; see
    ``elastic_stats``).  Elastic runs use the serial per-window transport
    (the commit doubles as the lease heartbeat); ``comm_overlap`` is
    inert under ``elastic=True``.  ``elastic=False`` (default) keeps the
    static-shard engine bit for bit.

    ``ps_core`` / ``coalesce`` / ``apply_kernel`` (PS engines only): the
    server-core knobs (docs/host_ps.md, "Event loop + coalescing").
    ``ps_core="event"`` (default) runs the selector-based core — one I/O
    thread multiplexing every worker connection, commits that arrive
    during an apply coalesced into one batched drain (one lock
    acquisition, one vectorized scatter-add per sparse run, one center
    snapshot per drain); ``"threaded"`` retains the seed thread-per-
    connection core (the ``host_ps_worker_scaling`` baseline).
    ``coalesce=False`` keeps the event loop but applies commits one at a
    time with per-commit reply snapshots — the sequential semantics.
    ``apply_kernel`` routes the apply arithmetic through the native
    ``csrc/applykernel.cpp`` scatter/axpy: ``None``/``"numpy"`` (default)
    is the pure-NumPy reference, ``"native"`` requires the built
    extension, ``"auto"`` uses it when available — results are
    bit-identical either way.

    ``recovery`` (``execution='host_ps'`` only): make the parameter servers
    themselves survivable (``resilience.py``).  A ``ShardSupervisor``
    journals periodic per-shard snapshots (center slice + clock, atomic
    writes) and heartbeats every shard (``'h'`` opcode through the apply
    lock, so a *wedged* apply fails the probe too); a dead shard is
    respawned on the same address from its last snapshot with its
    generation bumped.  Workers reconnect-resume mid-run under
    ``recovery_policy`` (a ``resilience.RetryPolicy``: attempts, backoff,
    jitter, deadline — default ``DEFAULT_RECOVERY_POLICY``), re-syncing
    with a pull; a restarted shard rejects in-flight commits stamped with
    the old generation.  Bounded-loss contract: windows committed after the
    shard's last snapshot are dropped — the same class of loss as the
    staleness the async algorithms already tolerate.  ``PSShardDown`` is
    raised only after the recovery deadline.  ``recovery=False`` (default)
    keeps the fail-fast PR 2 behavior bit for bit.

    ``ps_bind_host`` / ``ps_advertise_host`` (``execution='host_ps'``):
    where the socket PS listens and what the workers (and any
    ``attach_ps`` serving engine) dial.  Both default to loopback —
    the historical single-host behavior, bit for bit.  Multi-host runs
    bind ``"0.0.0.0"`` and advertise a routable interface
    (``networking.determine_host_address()`` — docs/DEPLOY.md); a
    wildcard bind with no explicit advertise falls back to advertising
    loopback, since a wildcard is listenable but not dialable.
    """

    #: algorithms whose per-algorithm comm_overlap default is ON
    _OVERLAP_DEFAULT_ON = ("downpour", "adag", "dynsgd")

    def __init__(self, keras_model, *, parallelism_factor: int = 1,
                 comm_overlap: Optional[bool] = None, ps_shards: int = 1,
                 recovery: bool = False, recovery_policy=None,
                 elastic: bool = False,
                 lease_windows: Optional[int] = None,
                 lease_timeout: float = 5.0,
                 ps_core: str = "event", coalesce: bool = True,
                 apply_kernel: Optional[str] = None,
                 stream: bool = False,
                 horizon_windows: Optional[int] = None,
                 max_horizons: Optional[int] = None,
                 row_sparse=None,
                 ps_bind_host: Optional[str] = None,
                 ps_advertise_host: Optional[str] = None,
                 ps_placement: str = "driver",
                 partition_windows: int = 0,
                 freeze_deadline: Optional[float] = None,
                 scratch_dir: Optional[str] = None,
                 **kw):
        super().__init__(keras_model, **kw)
        self.parallelism_factor = int(parallelism_factor)
        if self.parallelism_factor < 1:
            raise ValueError("parallelism_factor must be >= 1")
        if self.parallelism_factor > 1 and self.execution != "host_ps":
            raise ValueError(
                "parallelism_factor > 1 requires execution='host_ps' (the "
                "SPMD engine runs exactly one worker per chip)")
        if comm_overlap is not None and self.execution not in (
                "host_ps", "process_ps"):
            raise ValueError(
                "comm_overlap applies to the PS transports (execution="
                "'host_ps'/'process_ps'); the SPMD program exchanges deltas "
                "over ICI inside XLA — there is no wire to overlap")
        self._comm_overlap = comm_overlap
        self.ps_shards = int(ps_shards)
        if self.ps_shards < 1:
            raise ValueError("ps_shards must be >= 1")
        if self.ps_shards > 1 and self.execution not in ("host_ps",
                                                         "process_ps"):
            raise ValueError(
                "ps_shards > 1 requires a PS engine (execution='host_ps'/"
                "'process_ps'); the SPMD engine exchanges deltas over ICI "
                "— no PS to shard")
        self.recovery = bool(recovery)
        self.recovery_policy = recovery_policy
        if self.recovery and self.execution not in ("host_ps",
                                                    "process_ps"):
            raise ValueError(
                "recovery=True requires a PS engine (execution='host_ps'/"
                "'process_ps'); the SPMD engine's recovery story is "
                "checkpoint_dir + train(resume=True)")
        if self.recovery and self.execution == "process_ps" \
                and self.recovery_policy is not None:
            raise ValueError(
                "process_ps cannot ship a recovery_policy object to worker "
                "processes (config travels as JSON) — workers use "
                "DEFAULT_RECOVERY_POLICY; tune it via host_ps or leave "
                "recovery_policy=None")
        self.elastic = bool(elastic)
        if self.elastic and self.execution not in ("host_ps",
                                                   "process_ps"):
            raise ValueError(
                "elastic=True requires a PS engine (execution='host_ps'/"
                "'process_ps'); the SPMD engine is bulk-synchronous — a "
                "lost participant is a lost collective")
        if self.recovery and self.execution == "process_ps" \
                and not self.elastic:
            raise ValueError(
                "recovery=True on execution='process_ps' requires "
                "elastic=True (the supervised cross-process engine); the "
                "static process engine keeps the fail-fast topology")
        self.lease_windows = (None if lease_windows is None
                              else int(lease_windows))
        if self.lease_windows is not None and self.lease_windows < 1:
            raise ValueError("lease_windows must be >= 1")
        self.lease_timeout = float(lease_timeout)
        if self.lease_timeout <= 0:
            raise ValueError("lease_timeout must be > 0")
        # PS server-core knobs: validated eagerly (a bad core name or an
        # unbuilt apply_kernel='native' must fail at construction, not in
        # a server thread mid-run); non-defaults rejected off the PS
        # engines, same contract as comm_overlap
        from .parameter_servers import PS_CORES
        from . import applykernel as _applykernel
        self.ps_core = str(ps_core)
        if self.ps_core not in PS_CORES:
            raise ValueError(
                f"ps_core must be one of {sorted(PS_CORES)}, got "
                f"{ps_core!r}")
        self.coalesce = bool(coalesce)
        _applykernel.resolve(apply_kernel)
        self.apply_kernel = apply_kernel
        if self.execution not in ("host_ps", "process_ps") and (
                self.ps_core != "event" or not self.coalesce
                or self.apply_kernel is not None):
            raise ValueError(
                "ps_core/coalesce/apply_kernel apply to the PS server "
                "(execution='host_ps'/'process_ps'); the SPMD engine has "
                "no socket server to configure")
        # streaming online learning (streaming.py): stream=True trains
        # from an unbounded streaming.StreamSource passed to train() — a
        # HORIZON loop re-leases horizon_windows communication windows at
        # a time through the elastic lease machinery (exactly-once
        # completion per horizon; elastic membership and straggler steal
        # carry over verbatim).  max_horizons bounds an unbounded source;
        # on_horizon(h, model) observes the live center per horizon.
        self.stream = bool(stream)
        if self.stream and self.execution != "host_ps":
            raise ValueError(
                "stream=True requires execution='host_ps' (the horizon "
                "loop drives the live socket PS; the SPMD engine shapes "
                "finite epochs, and process_ps ships finite shards)")
        self.horizon_windows = (None if horizon_windows is None
                                else int(horizon_windows))
        if self.horizon_windows is not None and self.horizon_windows < 1:
            raise ValueError("horizon_windows must be >= 1")
        if self.horizon_windows is not None and not self.stream:
            raise ValueError("horizon_windows applies to stream=True")
        self.max_horizons = (None if max_horizons is None
                             else int(max_horizons))
        if self.max_horizons is not None and self.max_horizons < 1:
            raise ValueError("max_horizons must be >= 1")
        if self.max_horizons is not None and not self.stream:
            raise ValueError("max_horizons applies to stream=True")
        self.on_horizon = None
        # row-sparse embedding commits (streaming.py / workers.py): True
        # auto-detects every Embedding table from the model spec, or pass
        # explicit weight-list indices.  Each table's window delta ships
        # as an EXACT networking.RowSparseDelta (touched rows only) in
        # the same 1-RTT 'u' window as the dense rest — commit bytes
        # scale with rows touched, not table size.  Delta family only;
        # exact, so it does not compose with the lossy wire codings.
        self.row_sparse = row_sparse if row_sparse else None
        if self.row_sparse is not None:
            if self.execution != "host_ps":
                raise ValueError(
                    "row_sparse requires execution='host_ps' (the SPMD "
                    "engine exchanges deltas over ICI; process_ps ships "
                    "config as JSON and keeps dense commits)")
            if self.ALGORITHM not in ("downpour", "adag", "dynsgd"):
                raise ValueError(
                    "row_sparse applies to the delta family "
                    "(DOWNPOUR/ADAG/DynSGD); the elastic family's force "
                    "term is dense by construction")
            if self.wire_dtype is not None:
                raise ValueError(
                    "row_sparse is the exact sparse profile and does not "
                    "compose with lossy wire_dtype codings — use "
                    "wire_dtype=None")
        # PS address knobs (docs/DEPLOY.md): the driver historically wrote
        # loopback into both the server bind and the worker config —
        # correct single-host, wrong the moment workers live on another
        # host (ROADMAP item 1).  ps_bind_host is the interface the socket
        # PS listens on ("0.0.0.0" for all); ps_advertise_host is the
        # address workers (and attach_ps engines) dial — defaults to the
        # bind host, falling back to loopback when the bind is a wildcard
        # (a wildcard is not dialable).  None/None keeps the loopback
        # behavior bit for bit.
        self.ps_bind_host = (None if ps_bind_host is None
                             else str(ps_bind_host))
        self.ps_advertise_host = (None if ps_advertise_host is None
                                  else str(ps_advertise_host))
        if self.ps_bind_host == "" or self.ps_advertise_host == "":
            raise ValueError(
                "ps_bind_host/ps_advertise_host must be a host string or "
                "None (empty string is neither bindable nor dialable)")
        if (self.ps_bind_host is not None
                or self.ps_advertise_host is not None) and \
                self.execution not in ("host_ps", "process_ps"):
            raise ValueError(
                "ps_bind_host/ps_advertise_host configure the socket PS "
                "address (execution='host_ps'/'process_ps'); the SPMD "
                "engine has no socket server")
        # cross-process supervision knobs (execution='process_ps' with
        # elastic=True — parameter_servers._run_process_elastic):
        #   ps_placement   "driver" hosts the (possibly sharded) PS inside
        #                  the driver; "process" runs each shard as its own
        #                  ps_shard_main OS process, journaled to the shared
        #                  scratch dir and respawned same-address on death.
        #   partition_windows  >0 lets a network-partitioned worker keep
        #                  computing into a pending-commit buffer of that
        #                  many windows, reconciling on heal (workers.py);
        #                  0 keeps the blocking reconnect-resume behavior.
        #   freeze_deadline    seconds of wire-heartbeat silence after which
        #                  a live-by-waitpid worker process is declared
        #                  frozen (SIGSTOP, swap death) and its leases
        #                  revoked for survivors to steal; None disables.
        #   scratch_dir    the shared scratch directory (NFS path for real
        #                  multi-host runs); None uses a driver-local
        #                  tempdir, correct for same-host processes.
        self.ps_placement = str(ps_placement)
        if self.ps_placement not in ("driver", "process"):
            raise ValueError(
                f"ps_placement must be 'driver' or 'process', got "
                f"{ps_placement!r}")
        self.partition_windows = int(partition_windows)
        if self.partition_windows < 0:
            raise ValueError("partition_windows must be >= 0")
        self.freeze_deadline = (None if freeze_deadline is None
                                else float(freeze_deadline))
        if self.freeze_deadline is not None and self.freeze_deadline <= 0:
            raise ValueError("freeze_deadline must be > 0")
        self.scratch_dir = None if scratch_dir is None else str(scratch_dir)
        _proc_elastic_only = {
            "ps_placement='process'": self.ps_placement == "process",
            "freeze_deadline": self.freeze_deadline is not None,
            "scratch_dir": self.scratch_dir is not None,
        }
        for knob, is_set in _proc_elastic_only.items():
            if is_set and not (self.execution == "process_ps"
                               and self.elastic):
                raise ValueError(
                    f"{knob} applies to the supervised cross-process "
                    "engine — execution='process_ps' with elastic=True")
        if self.partition_windows and self.execution not in (
                "host_ps", "process_ps"):
            raise ValueError(
                "partition_windows applies to the PS transports "
                "(execution='host_ps'/'process_ps'); the SPMD engine has "
                "no wire to partition")
        if self.partition_windows and self.ps_shards > 1:
            raise ValueError(
                "partition_windows requires ps_shards=1 — sharded workers "
                "heal by blocking reconnect-resume (lease stealing already "
                "guarantees zero lost examples)")
        if (self.partition_windows and self.recovery
                and self.execution == "host_ps"):
            raise ValueError(
                "partition_windows with recovery is a process_ps feature — "
                "host_ps recovery routes workers through the sharded client, "
                "which heals by reconnect-resume")
        #: per-run streaming observability: horizons, rows ingested,
        #: examples/sec, buffer counters (run_stream_training)
        self.stream_stats: dict = {}
        #: elastic-run observability (resilience events): respawns, lease
        #: reassignments, per-worker windows, per-epoch exactly-once reports
        self.elastic_stats: dict = {}

    @property
    def comm_overlap(self) -> bool:
        if getattr(self, "row_sparse", None) is not None:
            # the row-sparse window step is itself ONE blocking 'u' round
            # trip (commit + fresh center, atomically) — the double-
            # buffered overlap loop has nothing to hide and doesn't carry
            # the mixed-delta rebase, so row_sparse pins the serial loop
            return False
        if self._comm_overlap is not None:
            return bool(self._comm_overlap)
        return self.ALGORITHM in self._OVERLAP_DEFAULT_ON


class SynchronousDistributedTrainer(DistributedTrainer):
    """Sync-family base (reference: same-named class; parallelism factor
    fixed at 1, as upstream)."""


class DOWNPOUR(AsynchronousDistributedTrainer):
    """DistBelief-style async SGD (reference: ``trainers.py :: DOWNPOUR``):
    workers push raw accumulated deltas every window (default 5) and re-pull
    the center.  SPMD form: center += Σᵢ Δᵢ each round."""
    ALGORITHM = "downpour"
    DEFAULT_WINDOW = 5


class ADAG(AsynchronousDistributedTrainer):
    """Asynchronous Distributed Adaptive Gradients (reference:
    ``trainers.py :: ADAG``) — the flagship/north-star algorithm.  Window
    deltas are normalized over commit count before applying: in bulk-sync form
    this is exactly an all-reduce *mean* of window deltas over ICI
    (center += Σᵢ Δᵢ / N)."""
    ALGORITHM = "adag"
    DEFAULT_WINDOW = 12


class DynSGD(AsynchronousDistributedTrainer):
    """Staleness-aware async SGD (reference: ``trainers.py :: DynSGD``,
    ``parameter_servers.py :: DynSGDParameterServer``): each commit is scaled
    by 1/(staleness+1).  SPMD form emulates serialized commits with a
    per-round rotation (see ``parallel/spmd.py``)."""
    ALGORITHM = "dynsgd"
    DEFAULT_WINDOW = 5


class AEASGD(AsynchronousDistributedTrainer):
    """Asynchronous Elastic Averaging SGD (Zhang et al. 2015; reference:
    ``trainers.py :: AEASGD``).  Worker keeps persistent local params; every
    window the elastic force α·(x−x̃) with α = learning_rate·rho is subtracted
    locally and added to the center."""
    ALGORITHM = "aeasgd"
    DEFAULT_WINDOW = 32

    def __init__(self, keras_model, rho: float = 5.0,
                 learning_rate: float = 0.1, **kw):
        super().__init__(keras_model, learning_rate=learning_rate, **kw)
        self.rho = float(rho)

    def _elastic_alpha(self) -> float:
        lr = self.learning_rate if self.learning_rate is not None else 0.1
        return self.rho * lr


class EAMSGD(AEASGD):
    """Elastic averaging with Nesterov momentum on the local update
    (reference: ``trainers.py :: EAMSGD``, ``momentum`` default 0.9).  The
    momentum lives in the worker optimizer (SGD+Nesterov); the elastic
    exchange is identical to AEASGD."""
    ALGORITHM = "eamsgd"

    def __init__(self, keras_model, rho: float = 5.0,
                 learning_rate: float = 0.1, momentum: float = 0.9, **kw):
        kw.pop("worker_optimizer", None)
        super().__init__(
            keras_model, rho=rho, learning_rate=learning_rate,
            worker_optimizer=opt_lib.SGD(learning_rate=learning_rate,
                                         momentum=momentum, nesterov=True),
            **kw)
        self.momentum = float(momentum)


def _reject_validation_kwargs(kw: dict, name: str) -> None:
    """The 'local' trainers never update a center model, so validating it
    per epoch would watch the INITIAL weights — refuse up front instead of
    accepting a kwarg that can never work."""
    if kw.get("early_stopping_patience") is not None:
        raise ValueError(
            f"{name} trains independent per-worker models (the center "
            "never moves): per-epoch center validation / early stopping "
            "does not apply")


class AveragingTrainer(DistributedTrainer):
    """One-shot parameter averaging (reference:
    ``trainers.py :: AveragingTrainer``): each worker trains independently on
    its shard; the result is the weight average."""
    ALGORITHM = "local"

    def __init__(self, keras_model, **kw):
        kw.setdefault("communication_window", 1)
        _reject_validation_kwargs(kw, type(self).__name__)
        super().__init__(keras_model, **kw)

    def train(self, dataset: Dataset, shuffle: bool = False,
              resume: bool = False) -> FittedModel:
        super().train(dataset, shuffle, resume)
        # average the per-worker local params (leading axis = workers)
        local = jax.device_get(self._state.local)
        avg = tmap(lambda v: np.mean(v, axis=0), local)
        self._fitted = FittedModel(self.master_model, avg)
        return self._fitted


class EnsembleTrainer(DistributedTrainer):
    """k independent models trained in parallel, returned as a list
    (reference: ``trainers.py :: EnsembleTrainer``)."""
    ALGORITHM = "local"

    def __init__(self, keras_model, num_models: Optional[int] = None, **kw):
        kw.setdefault("communication_window", 1)
        if num_models is not None:
            kw.setdefault("num_workers", num_models)
        _reject_validation_kwargs(kw, type(self).__name__)
        super().__init__(keras_model, **kw)
        self.num_models = self.num_workers

    def train(self, dataset: Dataset, shuffle: bool = False,
              resume: bool = False) -> List[FittedModel]:
        super().train(dataset, shuffle, resume)
        local = jax.device_get(self._state.local)
        models = []
        for i in range(self.num_workers):
            params_i = tmap(lambda v: v[i], local)
            models.append(FittedModel(self.master_model, params_i))
        self._ensemble = models
        self._fitted = models[0]  # predict-convenience surface only
        return models

    def serialize(self) -> dict:
        """All trained members: ``{"ensemble": [blob, ...]}`` (round-2
        VERDICT weak #10: returning just member 0 silently lost the rest).
        Rebuild with ``FittedModel.deserialize`` per entry."""
        if not getattr(self, "_ensemble", None):
            raise ValueError(
                "EnsembleTrainer has no fitted models yet; call train() first")
        return {"ensemble": [m.serialize() for m in self._ensemble]}
