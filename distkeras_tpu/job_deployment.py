"""Job deployment — remote/multi-host job submission and the punchcard queue.

Reference being replaced: ``distkeras/job_deployment.py :: Job`` + the
"Punchcard" job-queue machinery (SURVEY.md §2.1 row 22) — experimental
SSH-based packaging and submission of training jobs to a Spark cluster, with a
secrets-file job queue.

TPU-native rework: a multi-host TPU program is one SPMD Python process per
host, all started with the same script and a shared coordinator address
(``jax.distributed.initialize``).  So deployment here means: render the
per-host environment (coordinator, process index/count), launch the script on
every host — over SSH for real pods, as local subprocesses for single-host or
testing — and collect exit status.  The punchcard survives as a file-backed
FIFO of pending jobs drained by a daemon loop.
"""

from __future__ import annotations

import contextlib
import json
import os
import shlex
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

COORDINATOR_PORT = 8476


class Job:
    """A deployable training job (reference: ``job_deployment.py :: Job``).

    Parameters
    ----------
    name: job identifier (used in logs and the punchcard queue).
    script: path to the Python training script to run on every host.
    args: extra argv passed to the script.
    hosts: hostnames of the pod slice; ``None``/empty → run locally.
    env: extra environment variables for the job processes.
    python: interpreter to use on the hosts.
    """

    def __init__(self, name: str, script: str,
                 args: Sequence[str] = (),
                 hosts: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 python: str = sys.executable,
                 coordinator_port: int = COORDINATOR_PORT,
                 coordinated: bool = True,
                 process_ids: Optional[Sequence[int]] = None):
        self.name = name
        self.script = script
        self.args = list(args)
        self.hosts = list(hosts) if hosts else []
        self.env = dict(env or {})
        self.python = python
        self.coordinator_port = int(coordinator_port)
        # process_ids: explicit id per host slot (default: the slot index).
        # Lets a supervisor respawn ONE member under a FRESH id through the
        # same runner — a single-host Job whose process_ids=[7] renders
        # DISTKERAS_TPU_PROCESS_ID=7, not 0.
        self.process_ids = (None if process_ids is None
                            else [int(i) for i in process_ids])
        # coordinated=False: processes are independent (no jax.distributed
        # group) — e.g. PS workers that only speak the socket wire; one
        # crashing must not stall the others at an init barrier
        self.coordinated = bool(coordinated)
        self.returncodes: List[int] = []
        self.processes: List[subprocess.Popen] = []

    # -- environment rendering ----------------------------------------------
    def host_env(self, process_id: int) -> Dict[str, str]:
        """Per-host env for ``jax.distributed.initialize`` discovery (or
        just the process id when ``coordinated=False``)."""
        num = max(len(self.hosts), 1)
        coordinator = (self.hosts[0] if self.hosts else "127.0.0.1")
        env = dict(self.env)
        if self.process_ids is not None:
            process_id = self.process_ids[process_id]
        env["DISTKERAS_TPU_PROCESS_ID"] = str(process_id)
        if self.coordinated:
            env.update({
                "DISTKERAS_TPU_COORDINATOR":
                    f"{coordinator}:{self.coordinator_port}",
                "DISTKERAS_TPU_NUM_PROCESSES": str(num),
            })
        else:
            # explicitly blank (not merely omit): launchers overlay this on
            # os.environ, and a driver that itself runs under a coordinated
            # Job must not leak its coordinator into uncoordinated children
            # (they would try to join the parent's jax.distributed group)
            env.update({"DISTKERAS_TPU_COORDINATOR": "",
                        "DISTKERAS_TPU_NUM_PROCESSES": "1"})
        return env

    def command(self) -> List[str]:
        return [self.python, self.script] + [str(a) for a in self.args]

    # -- execution ------------------------------------------------------------
    def run(self, runner: Optional["JobRunner"] = None, wait: bool = True
            ) -> int:
        """Launch on all hosts (reference: ``Job.run``). Returns the max exit
        code (0 = every host succeeded).  With ``wait=False`` the handles stay
        in ``self.processes``; call ``wait()`` later to reap them."""
        if runner is None:
            runner = SSHJobRunner() if self.hosts else LocalJobRunner()
        self.processes = runner.launch(self)
        if not wait:
            return 0
        return self.wait()

    def wait(self) -> int:
        """Reap launched processes; returns the max exit code."""
        self.returncodes = [p.wait() for p in self.processes]
        return max(self.returncodes, default=0)

    # -- punchcard (de)serialization ------------------------------------------
    def to_record(self) -> dict:
        return {"name": self.name, "script": self.script, "args": self.args,
                "hosts": self.hosts, "env": self.env, "python": self.python,
                "coordinator_port": self.coordinator_port,
                "coordinated": self.coordinated,
                "process_ids": self.process_ids}

    @classmethod
    def from_record(cls, rec: dict) -> "Job":
        return cls(rec["name"], rec["script"], rec.get("args", ()),
                   rec.get("hosts"), rec.get("env"),
                   rec.get("python", sys.executable),
                   rec.get("coordinator_port", COORDINATOR_PORT),
                   rec.get("coordinated", True),
                   rec.get("process_ids"))


class JobRunner:
    def launch(self, job: Job) -> List[subprocess.Popen]:  # pragma: no cover
        raise NotImplementedError


class LocalJobRunner(JobRunner):
    """Run every "host" as a local subprocess — single-host deployment and the
    test double for SSH (the reference's equivalent was Spark ``local[*]``
    mode, SURVEY.md §4)."""

    def launch(self, job: Job) -> List[subprocess.Popen]:
        n = max(len(job.hosts), 1)
        procs = []
        for pid in range(n):
            env = dict(os.environ)
            env.update(job.host_env(pid))
            procs.append(subprocess.Popen(job.command(), env=env))
        return procs


class SSHJobRunner(JobRunner):
    """Launch the job script on each pod host over SSH (reference:
    ``job_deployment.py`` SSH submission).  Assumes the repo/script path is
    visible on the hosts (shared filesystem or pre-synced image)."""

    def __init__(self, ssh_binary: str = "ssh",
                 ssh_options: Sequence[str] = ("-o", "BatchMode=yes")):
        self.ssh_binary = ssh_binary
        self.ssh_options = list(ssh_options)

    def launch(self, job: Job) -> List[subprocess.Popen]:
        if not job.hosts:
            raise ValueError("SSHJobRunner needs job.hosts")
        procs = []
        for pid, host in enumerate(job.hosts):
            env = job.host_env(pid)
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in env.items())
            remote = f"env {exports} " + " ".join(
                shlex.quote(c) for c in job.command())
            cmd = [self.ssh_binary, *self.ssh_options, host, remote]
            procs.append(subprocess.Popen(cmd))
        return procs


class Punchcard:
    """File-backed FIFO job queue (reference: the "punchcard" daemon).

    The queue file holds one JSON job record per line; ``submit`` appends,
    ``pop`` removes the head.  A daemon drains it with ``serve`` — the
    reference's punchcard loop, minus the secrets file (auth is SSH's
    problem, not the queue's).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock_path = path + ".lock"

    @contextlib.contextmanager
    def _locked(self):
        """Advisory flock serializing submit/pop across processes — a
        concurrent submit during a pop must not be lost in the rewrite."""
        import fcntl  # Unix-only; keep the package importable elsewhere
        with open(self._lock_path, "a") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)

    def submit(self, job: Job) -> None:
        with self._locked():
            with open(self.path, "a") as f:
                f.write(json.dumps(job.to_record()) + "\n")

    def _read(self) -> List[Job]:
        if not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            return [Job.from_record(json.loads(line))
                    for line in f if line.strip()]

    def pending(self) -> List[Job]:
        with self._locked():
            return self._read()

    def pop(self) -> Optional[Job]:
        with self._locked():
            jobs = self._read()
            if not jobs:
                return None
            # atomic rewrite: a crash mid-pop must not lose pending jobs
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                for j in jobs[1:]:
                    f.write(json.dumps(j.to_record()) + "\n")
            os.replace(tmp, self.path)
            return jobs[0]

    def run_once(self, runner: Optional[JobRunner] = None) -> Optional[int]:
        """Pop and run the head job; None if the queue is empty."""
        job = self.pop()
        if job is None:
            return None
        return job.run(runner)

    def serve(self, runner: Optional[JobRunner] = None,
              poll_interval: float = 1.0, max_jobs: Optional[int] = None
              ) -> int:
        """Drain the queue: run jobs until it is empty or ``max_jobs`` have
        run. Returns the number of jobs executed.  ``poll_interval`` spaces
        successive jobs out (the reference punchcard daemon throttled the
        same way); an empty queue always returns."""
        done = 0
        while max_jobs is None or done < max_jobs:
            rc = self.run_once(runner)
            if rc is None:
                break
            done += 1
            if poll_interval and (max_jobs is None or done < max_jobs):
                time.sleep(poll_interval)
        return done


def initialize_from_env() -> None:
    """Call ``jax.distributed.initialize`` from the env vars ``Job`` renders —
    the first line of a deployed multi-host training script."""
    coord = os.environ.get("DISTKERAS_TPU_COORDINATOR")
    if not coord:
        return  # single-process run; nothing to initialize
    num = int(os.environ.get("DISTKERAS_TPU_NUM_PROCESSES", "1"))
    pid = int(os.environ.get("DISTKERAS_TPU_PROCESS_ID", "0"))
    if num <= 1:
        return
    import jax
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=num, process_id=pid)
