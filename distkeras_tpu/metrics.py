"""Structured metrics + profiler tracing.

The reference's observability is wall-clock only —
``Trainer.record_training_start/stop`` plus loss-history lists collected from
workers, and scattered ``print`` statements (SURVEY.md §5).  Here metrics are
structured events (JSONL) with throughput derived per epoch, and ``trace()``
wraps ``jax.profiler`` so a TensorBoard-readable device trace is one context
manager away — required plumbing for the examples/sec/chip north-star metric.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO, Any, Dict, List, Optional


class MetricsLogger:
    """Append-only JSONL event log + in-memory history.

    Events carry a monotonic wall-clock ``t`` and arbitrary scalar fields:
    ``log(step=3, loss=0.7, examples_per_sec=1e6)``.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.events: List[Dict[str, Any]] = []
        self._fh: Optional[IO[str]] = open(path, "a") if path else None

    def log(self, **fields) -> Dict[str, Any]:
        # absolute wall time: stays monotonic when a resumed run appends to
        # the same JSONL file
        event = {"t": round(time.time(), 6)}
        event.update({k: (float(v) if hasattr(v, "item") else v)
                      for k, v in fields.items()})
        self.events.append(event)
        if self._fh:
            self._fh.write(json.dumps(event) + "\n")
            self._fh.flush()
        return event

    def scalar_series(self, field: str) -> List[float]:
        return [e[field] for e in self.events if field in e]

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


class EpochMetrics:
    """Derives per-epoch throughput for the trainers: examples/sec and
    examples/sec/chip from (rows, seconds, num_chips)."""

    def __init__(self, logger: Optional[MetricsLogger] = None,
                 num_chips: int = 1):
        self.logger = logger or MetricsLogger()
        self.num_chips = max(int(num_chips), 1)

    def epoch(self, epoch: int, examples: int, seconds: float,
              mean_loss: float) -> Dict[str, Any]:
        eps = examples / seconds if seconds > 0 else float("inf")
        return self.logger.log(
            kind="epoch", epoch=epoch, examples=examples,
            seconds=round(seconds, 6), loss=mean_loss,
            examples_per_sec=round(eps, 2),
            examples_per_sec_per_chip=round(eps / self.num_chips, 2))


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True):
    """Capture a ``jax.profiler`` device trace for the enclosed block
    (view with TensorBoard / Perfetto).  No-ops cleanly when disabled."""
    if not enabled:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the profiler timeline (jax.profiler.TraceAnnotation)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield


# -- analytic FLOPs + MFU ----------------------------------------------------

# bf16 peak FLOP/s per chip by device_kind substring (public spec sheets);
# first match wins, so more specific entries come first
_PEAK_FLOPS = (
    ("TPU v6 lite", 918e12),   # Trillium
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5p", 459e12),
    ("TPU v5", 459e12),
    ("TPU v4 lite", 138e12),   # v4i
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 46e12),
)


def peak_flops(device_kind: str) -> Optional[float]:
    """bf16 peak FLOP/s for a ``jax.devices()[0].device_kind`` string, or
    None when unknown (CPU, new hardware) — callers emit mfu=null then."""
    for key, val in _PEAK_FLOPS:
        if key.lower() in str(device_kind).lower():
            return val
    return None


def _attention_flops(layer, in_shape) -> float:
    """Matmul FLOPs of one attention layer on one example.

    Sizes the k/v projections by ``num_kv_heads`` so GQA/MQA models are not
    overcounted (q/o stay full-width: ``num_heads * key_dim``), and caps the
    score/value matmuls at the sliding-window width when one is set.
    """
    s, d = in_shape
    inner = layer.num_heads * layer.key_dim
    kv_heads = layer.num_kv_heads or layer.num_heads
    inner_kv = kv_heads * layer.key_dim
    total = 2.0 * s * d * (inner + 2.0 * inner_kv)  # q + k + v projections
    total += 2.0 * s * inner * d                  # output projection
    window = getattr(layer, "attention_window", None)
    ctx = float(min(s, window + 1)) if window is not None else float(s)
    total += 2.0 * 2.0 * s * ctx * inner          # qk^T and scores@v
    return total


def flops_per_example(model, backward: bool = True) -> float:
    """Analytic matmul/conv FLOPs for one example through a ``Sequential``.

    Counts the MXU work only (Dense 2·m·k·n, Conv2D 2·Ho·Wo·kh·kw·cin·cout,
    attention/MLP projections inside TransformerBlock); elementwise/pooling
    FLOPs are negligible against these.  ``backward=True`` applies the
    standard 3x rule (forward + ~2x for the two backward matmuls per
    forward matmul) — the number MFU is judged against.
    """
    import jax
    import numpy as np
    from .core import layers as L

    if model.input_shape is None:
        raise ValueError("model has no input_shape")
    shape = tuple(model.input_shape)
    rng = jax.random.PRNGKey(0)
    total = 0.0
    for layer in model.layers:
        _, out_shape = layer.init(rng, shape)
        if isinstance(layer, L.Dense):
            rows = float(np.prod(shape[:-1])) if len(shape) > 1 else 1.0
            total += 2.0 * rows * shape[-1] * layer.units
        elif isinstance(layer, L.Conv2D):
            ho, wo, _ = out_shape
            kh, kw = layer.kernel_size
            total += 2.0 * ho * wo * kh * kw * shape[-1] * layer.filters
        elif isinstance(layer, L.Embedding):
            pass  # gather, not matmul
        elif isinstance(layer, L.MultiHeadAttention):
            total += _attention_flops(layer, shape)
        elif isinstance(layer, L.TransformerBlock):
            s, d = shape
            total += _attention_flops(layer, shape)
            total += 2.0 * s * d * layer.mlp_dim * 2  # mlp in+out
        shape = out_shape
    return total * (3.0 if backward else 1.0)
