"""Structured metrics + profiler tracing.

The reference's observability is wall-clock only —
``Trainer.record_training_start/stop`` plus loss-history lists collected from
workers, and scattered ``print`` statements (SURVEY.md §5).  Here metrics are
structured events (JSONL) with throughput derived per epoch, and ``trace()``
wraps ``jax.profiler`` so a TensorBoard-readable device trace is one context
manager away — required plumbing for the examples/sec/chip north-star metric.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import IO, Any, Dict, List, Optional


class MetricsLogger:
    """Append-only JSONL event log + in-memory history.

    Events carry a monotonic wall-clock ``t`` and arbitrary scalar fields:
    ``log(step=3, loss=0.7, examples_per_sec=1e6)``.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.events: List[Dict[str, Any]] = []
        self._fh: Optional[IO[str]] = open(path, "a") if path else None

    def log(self, **fields) -> Dict[str, Any]:
        # absolute wall time: stays monotonic when a resumed run appends to
        # the same JSONL file
        event = {"t": round(time.time(), 6)}
        event.update({k: (float(v) if hasattr(v, "item") else v)
                      for k, v in fields.items()})
        self.events.append(event)
        if self._fh:
            self._fh.write(json.dumps(event) + "\n")
            self._fh.flush()
        return event

    def scalar_series(self, field: str) -> List[float]:
        return [e[field] for e in self.events if field in e]

    def close(self):
        if self._fh:
            self._fh.close()
            self._fh = None


class EpochMetrics:
    """Derives per-epoch throughput for the trainers: examples/sec and
    examples/sec/chip from (rows, seconds, num_chips)."""

    def __init__(self, logger: Optional[MetricsLogger] = None,
                 num_chips: int = 1):
        self.logger = logger or MetricsLogger()
        self.num_chips = max(int(num_chips), 1)

    def epoch(self, epoch: int, examples: int, seconds: float,
              mean_loss: float) -> Dict[str, Any]:
        eps = examples / seconds if seconds > 0 else float("inf")
        return self.logger.log(
            kind="epoch", epoch=epoch, examples=examples,
            seconds=round(seconds, 6), loss=mean_loss,
            examples_per_sec=round(eps, 2),
            examples_per_sec_per_chip=round(eps / self.num_chips, 2))


@contextlib.contextmanager
def trace(log_dir: str, enabled: bool = True):
    """Capture a ``jax.profiler`` device trace for the enclosed block
    (view with TensorBoard / Perfetto).  No-ops cleanly when disabled."""
    if not enabled:
        yield
        return
    import jax
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Named region in the profiler timeline (jax.profiler.TraceAnnotation)."""
    import jax
    with jax.profiler.TraceAnnotation(name):
        yield
