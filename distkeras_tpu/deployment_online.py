"""Online deployment: train-while-serve under one lifecycle (ROADMAP item 5).

The paper's whole premise is asynchronous trainers (DOWNPOUR/ADAG) feeding a
live parameter server; this repo already grew both production halves — PR 10
trains continuously from an unbounded stream, PRs 6/8/9/11/12 serve with
``attach_ps`` hot reload — and this module runs them as ONE system:

.. code-block:: text

        traffic ──▶ OnlineDeployment.serve() ──▶ ServingEngine ──┐
           ▲              │ feed(x, y)                           │ 'p' pull
           │              ▼                                      ▼
        clients      StreamSource ──▶ run_stream_training ──▶ socket PS
                     (stamped)         (elastic host-PS)      (live center)

one process graph under one supervisor surface, chaos-killable at every
seam by COMPOSING the existing machinery rather than duplicating it:

 - **workers** die and respawn through the streaming trainer's own
   ``WorkerSupervisor`` + ``LeaseLedger`` (exactly-once per horizon);
 - **PS shards** die and respawn same-address through ``ShardSupervisor``
   (``recovery=True``); the engine's reload socket re-dials under a
   ``resilience.RetryPolicy`` and a failed pull keeps the current weights;
 - **the serving engine** dies (crash or wedge) and is respawned through
   ``EngineSupervisor`` — the deployment itself is the supervisor's
   ``target``, so the detect→``respawn_clone``→``warmup``→swap path lands
   on the deployment's atomic ``engine`` setter and bumps the serve
   generation exactly like a blue/green swap does.

**Freshness** is the first-class observable: every example is stamped when
it enters the stream (``feed()`` time for served-traffic feedback rows,
read-arrival time for base chunks), every completed horizon stamps the
commit instant (by ``on_horizon`` every row of horizon *h* is applied to
the live center), and every successful ``attach_ps`` pull closes the loop
through the engine's reload listener — the pulled center's update clock is
``stats["center_generation"]``, and the next decode step serves it.  One
freshness sample per stamped chunk:

    ``freshness_s = t_pull_live - t_stream_entry``

reported as ``freshness_p50_s`` / ``freshness_p99_s`` (row-weighted
percentiles) in :meth:`OnlineDeployment.stats`, mirrored into
``trainer.stream_stats`` and ``engine.stats``, and surfaced as bench
fields (``bench.py``).

**Blue/green reload** (:meth:`OnlineDeployment.blue_green_swap`): serve
generation *g* while *g+1* warms — a ``respawn_clone()`` pulls the
freshest center, ``warmup()`` precompiles every program, and only then
does the atomic engine swap land; the old engine drains (in-flight
requests finish on *g*), so a request is served by exactly one generation
end to end.

Constructing no ``OnlineDeployment`` changes nothing: the trainer hooks
(``_on_ps_ready``, ``on_horizon``) default to None, the engine's reload
listener defaults to None, and the stamped-source wrapper only exists
inside a deployment (asserted in tests/test_online_deployment.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .resilience import EngineSupervisor
from .serving import EngineDead, ServingEngine
from .streaming import StreamSource

logger = logging.getLogger("distkeras_tpu.deployment_online")


# ---------------------------------------------------------------------------
# freshness: stream entry → PS commit → attach_ps pull
# ---------------------------------------------------------------------------

def _weighted_percentile(samples: Sequence[Tuple[float, int]],
                         q: float) -> Optional[float]:
    """Row-weighted percentile over ``(value, rows)`` samples — every
    stamped row counts once without materializing a per-row array."""
    if not samples:
        return None
    ordered = sorted(samples)
    total = sum(w for _, w in ordered)
    target = q / 100.0 * total
    seen = 0
    for value, w in ordered:
        seen += w
        if seen >= target:
            return value
    return ordered[-1][0]


class FreshnessTracker:
    """Time-to-served-effect accounting across the three online seams.

    Called from three threads — the stream consumer (``note_horizon``),
    the training thread's horizon loop (``note_commit``), and the engine's
    decode thread (``note_pull``, via the engine's reload listener) — so
    every transition holds the tracker lock.  All instants are
    ``time.monotonic()``.

     - :meth:`note_horizon` — one call per stream read (one read = one
       horizon in ``run_stream_training``); ``entries`` is the chunk
       breakdown ``[(rows, t_entry), ...]`` so feedback rows keep their
       ``feed()``-time stamps while base rows carry arrival time.
     - :meth:`note_commit` — horizon *h* completed: by ``on_horizon``
       every one of its rows is applied to the live center.
     - :meth:`note_pull` — a successful hot-reload pull at instant *t*
       with the center's update clock: every committed-but-unserved
       horizon whose commit predates *t* becomes served, one freshness
       sample per stamped chunk (the next decode step serves the pulled
       weights — pull instants are taken between steps).
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: per horizon: {"chunks": [(rows, t_entry)], "committed": t|None,
        #:  "served": t|None}
        self._horizons: List[Dict[str, Any]] = []
        self._samples: List[Tuple[float, int]] = []   # (freshness_s, rows)
        self.pulls = 0
        self.last_pull_generation: Optional[int] = None

    def note_horizon(self, entries: Sequence[Tuple[int, float]]) -> int:
        with self._lock:
            self._horizons.append({"chunks": [(int(n), float(t))
                                              for n, t in entries],
                                   "committed": None, "served": None})
            return len(self._horizons) - 1

    def note_commit(self, horizon: int, t: Optional[float] = None) -> None:
        t = time.monotonic() if t is None else float(t)
        with self._lock:
            if 0 <= horizon < len(self._horizons):
                h = self._horizons[horizon]
                if h["committed"] is None:
                    h["committed"] = t

    def note_pull(self, t: float, generation: Optional[int]) -> None:
        with self._lock:
            self.pulls += 1
            if generation is not None:
                self.last_pull_generation = int(generation)
            for h in self._horizons:
                if (h["served"] is None and h["committed"] is not None
                        and h["committed"] <= t):
                    h["served"] = t
                    for rows, t_entry in h["chunks"]:
                        self._samples.append(
                            (max(float(t) - t_entry, 0.0), rows))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            rows = sum(w for _, w in self._samples)
            served = sum(1 for h in self._horizons
                         if h["served"] is not None)
            committed = sum(1 for h in self._horizons
                            if h["committed"] is not None)
            return {
                "freshness_p50_s": _weighted_percentile(self._samples, 50),
                "freshness_p99_s": _weighted_percentile(self._samples, 99),
                "freshness_rows": rows,
                "freshness_horizons_served": served,
                "freshness_horizons_committed": committed,
                "reload_pulls": self.pulls,
                "center_generation": self.last_pull_generation,
            }


# ---------------------------------------------------------------------------
# the stamped + feedback stream source
# ---------------------------------------------------------------------------

class _DeployedSource(StreamSource):
    """The deployment's view of the caller's :class:`StreamSource`:
    every read is stamped for freshness, and served-traffic feedback rows
    (:meth:`OnlineDeployment.feed`) are spliced in ahead of base rows —
    the served→trained feedback loop.  Subclasses ``StreamSource`` only
    to satisfy the streaming trainer's contract check; all state lives on
    the wrapped base source."""

    # deliberately no super().__init__: this wrapper owns no backend —
    # read/start/stop delegate, and `buffer` aliases the base's ring so
    # run_stream_training's buffer accounting observes the real stream
    def __init__(self, base: StreamSource, tracker: FreshnessTracker):
        self._base = base
        self._tracker = tracker
        self._fb_lock = threading.Lock()
        #: pending feedback chunks: (x, y, t_feed)
        self._fb: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self.rows_fed_back = 0
        self._closed = False

    @property
    def buffer(self):
        return self._base.buffer

    def start(self) -> "StreamSource":
        self._base.start()
        return self

    def stop(self) -> None:
        # feedback makes the stream SELF-SUSTAINING (every served batch
        # fed back becomes a future horizon), so closing the base alone
        # would never end the run — the closed flag stops the splice,
        # abandoning unconsumed feedback, while buffered base rows still
        # drain (zero lost base examples)
        self._closed = True
        self._base.stop()

    def feed(self, x: np.ndarray, y: np.ndarray) -> int:
        x = np.asarray(x)
        y = np.asarray(y)
        if len(x) != len(y):
            raise ValueError(f"feedback rows disagree: x has {len(x)}, "
                             f"y has {len(y)}")
        if len(x) == 0:
            return 0
        with self._fb_lock:
            self._fb.append((x.copy(), y.copy(), time.monotonic()))
            self.rows_fed_back += len(x)
        return len(x)

    def read(self, n: int, timeout: Optional[float] = None):
        if self._closed:
            chunk = self._base.read(n, timeout=timeout)  # drain the tail
            if chunk is None:
                return None
            self._tracker.note_horizon([(len(chunk[0]), time.monotonic())])
            return chunk
        with self._fb_lock:
            pending, self._fb = self._fb, []
        fb_rows = sum(len(x) for x, _, _ in pending)
        base_chunk = None
        if fb_rows < n:
            base_chunk = self._base.read(n - fb_rows, timeout=timeout)
        if not pending and base_chunk is None:
            return None  # base stream drained, no feedback queued
        entries: List[Tuple[int, float]] = [(len(x), t)
                                            for x, _, t in pending]
        parts_x = [x for x, _, _ in pending]
        parts_y = [y for _, y, _ in pending]
        if base_chunk is not None:
            # base rows are stamped at arrival — the instant they leave
            # the source and become trainable (docs/DEPLOY.md defines the
            # freshness clock start per row class)
            entries.append((len(base_chunk[0]), time.monotonic()))
            parts_x.append(base_chunk[0])
            parts_y.append(base_chunk[1])
        self._tracker.note_horizon(entries)
        if len(parts_x) == 1:
            return parts_x[0], parts_y[0]
        return np.concatenate(parts_x), np.concatenate(parts_y)


# ---------------------------------------------------------------------------
# the deployment supervisor
# ---------------------------------------------------------------------------

class OnlineDeployment:
    """Run the canonical online-ML process graph under one lifecycle.

    ``trainer`` is a stream-mode async PS trainer (``stream=True``,
    ``execution='host_ps'``), ``source`` the unbounded
    :class:`~distkeras_tpu.streaming.StreamSource` it trains from, and
    ``engine`` a :class:`~distkeras_tpu.serving.ServingEngine` over the
    SAME architecture (the hot-reload pull maps the PS center onto the
    engine's weight list — a mismatched architecture fails the pull and
    counts ``reload_failures``; it never corrupts serving).

    :meth:`start` wires the seams and launches training on a background
    thread: the source is wrapped for freshness stamping + feedback, the
    trainer's ``_on_ps_ready`` hook attaches the engine to the live PS the
    moment its address exists, and ``on_horizon`` is chained (freshness
    commit stamp first, then the caller's hook).  The engine may be
    ``start()``-ed (live mode — its decode loop pulls between steps) or
    inline (``serve`` pumps ``step()`` on the caller's thread — the
    deterministic tier-1 test path).

    ``supervise=True`` starts an :class:`EngineSupervisor` with the
    DEPLOYMENT as its target: a crashed or wedged engine is respawned
    (``respawn_clone`` → ``warmup`` → ``start``) and swapped in through
    the same atomic ``engine`` setter blue/green uses, bumping
    ``generation``.  Requests in flight at the kill fail with
    :class:`EngineDead`; :meth:`serve` resubmits them to the replacement
    (deterministic seeds make the retry idempotent), so a chaos kill
    loses zero requests end to end.

    Chaos surface (composing, not duplicating): :meth:`kill_engine`
    (→ ``EngineSupervisor`` recovery), :meth:`kill_ps_shard`
    (→ ``ShardSupervisor`` same-address respawn; needs ``recovery=True``
    on the trainer), and worker kills via the trainer's own
    ``fault_injection`` knob (→ ``WorkerSupervisor`` respawn under the
    exactly-once lease ledger).
    """

    def __init__(self, trainer, source: StreamSource,
                 engine: ServingEngine, *, reload_every: int = 1,
                 reload_retry_policy=None, supervise: bool = False,
                 supervisor_kw: Optional[Dict[str, Any]] = None):
        if not getattr(trainer, "stream", False):
            raise ValueError(
                "OnlineDeployment drives the streaming horizon loop — "
                "construct the trainer with stream=True "
                "(execution='host_ps')")
        if not isinstance(source, StreamSource):
            raise ValueError(
                f"source must be a streaming.StreamSource, got "
                f"{type(source).__name__}")
        if not isinstance(engine, ServingEngine):
            raise ValueError(
                f"engine must be a serving.ServingEngine, got "
                f"{type(engine).__name__}")
        if engine._ps_addr is not None:
            raise ValueError(
                "engine is already attach_ps-ed; the deployment owns the "
                "attachment (it learns the PS address from the training "
                "run)")
        if int(reload_every) < 1:
            raise ValueError(f"reload_every must be >= 1, "
                             f"got {reload_every}")
        self.trainer = trainer
        self.tracker = FreshnessTracker()
        self.source = _DeployedSource(source, self.tracker)
        self.reload_every = int(reload_every)
        self.reload_retry_policy = reload_retry_policy
        self._engine = engine
        self._lock = threading.Lock()        # engine identity + generation
        self.generation = 0                  # serve generation (g)
        #: one record per engine swap (blue/green or supervised restart)
        self.swaps: List[Dict[str, Any]] = []
        self.supervisor: Optional[EngineSupervisor] = None
        self._supervise = bool(supervise)
        self._supervisor_kw = dict(supervisor_kw or {})
        self._train_thread: Optional[threading.Thread] = None
        self._train_error: Optional[BaseException] = None
        self._fitted = None
        self._done = threading.Event()
        self._ps_ready = threading.Event()
        self.ps_addr: Optional[Tuple[str, int]] = None
        self._user_on_horizon: Optional[Callable] = None
        self._started = False

    # -- engine identity (the atomic swap seam) ------------------------------
    @property
    def engine(self) -> ServingEngine:
        return self._engine

    @engine.setter
    def engine(self, new: ServingEngine) -> None:
        # EngineSupervisor._recover assigns here (`target.engine = new`)
        # and blue_green_swap routes through the same setter: ONE atomic
        # transition bumps the serve generation, so every submit observes
        # a consistent (engine, generation) pair
        with self._lock:
            old, self._engine = self._engine, new
            self.generation += 1
            self.swaps.append({
                "generation": self.generation,
                "old_engine": id(old), "new_engine": id(new),
                "old_dead": old.dead is not None,
            })

    def _current(self) -> Tuple[ServingEngine, int]:
        with self._lock:
            return self._engine, self.generation

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "OnlineDeployment":
        if self._started:
            raise RuntimeError("OnlineDeployment.start() is one-shot")
        self._started = True
        with self._lock:
            self._engine._reload_listener = self.tracker.note_pull
        self.trainer._on_ps_ready = self._on_ps_ready
        self._user_on_horizon = getattr(self.trainer, "on_horizon", None)
        self.trainer.on_horizon = self._on_horizon
        if self._supervise:
            self.supervisor = EngineSupervisor(self, **self._supervisor_kw)
            self.supervisor.start()
        self._train_thread = threading.Thread(
            target=self._train, daemon=True, name="dkt-online-trainer")
        self._train_thread.start()
        return self

    def _on_ps_ready(self, server, addr: Tuple[str, int]) -> None:
        self.ps_addr = (str(addr[0]), int(addr[1]))
        eng, _ = self._current()
        # sharded training PS (ps_shards>1): the streaming run hands this
        # hook the live ShardedServerGroup — attach the engine with its
        # plan + per-shard ports so every hot-reload pull gathers the FULL
        # center (attach_ps's all-or-nothing sharded path), never one
        # shard's torn slice.  The advertise host comes from `addr`; the
        # group's ports are bind-side but port numbers are host-agnostic.
        plan = getattr(server, "plan", None)
        shard_kw = {}
        if plan is not None and getattr(plan, "num_shards", 1) > 1:
            shard_kw = {"shard_plan": plan,
                        "shard_addrs": [(self.ps_addr[0], int(p))
                                        for p in server.ports]}
        eng.attach_ps(*self.ps_addr, every=self.reload_every,
                      retry_policy=self.reload_retry_policy, **shard_kw)
        self._ps_ready.set()

    def _on_horizon(self, h: int, fitted) -> None:
        self.tracker.note_commit(h)
        if self._user_on_horizon is not None:
            self._user_on_horizon(h, fitted)

    def _train(self) -> None:
        try:
            self._fitted = self.trainer.train(self.source)
        except BaseException as e:
            self._train_error = e
            logger.exception("online deployment training run failed")
        finally:
            self._ps_ready.set()  # unblock waiters even on early failure
            self._publish_freshness()
            self._done.set()

    def _publish_freshness(self) -> None:
        """Mirror the freshness observables into trainer/engine stats —
        the contract surface ISSUE 15 names (bench reads them here)."""
        fresh = self.tracker.stats()
        stats = getattr(self.trainer, "stream_stats", None)
        if isinstance(stats, dict):
            stats.update({k: fresh[k] for k in
                          ("freshness_p50_s", "freshness_p99_s",
                           "freshness_rows")})
        eng, _ = self._current()
        eng.stats["freshness_p50_s"] = fresh["freshness_p50_s"]
        eng.stats["freshness_p99_s"] = fresh["freshness_p99_s"]

    def wait_ps_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the training run's PS exists and the engine is
        attached (or training already ended)."""
        return self._ps_ready.wait(timeout)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def join(self, timeout: Optional[float] = None):
        """Wait for the training run to end (stream drained or
        ``max_horizons`` reached); returns the fitted model.  Re-raises
        the training thread's error, if any."""
        if self._train_thread is None:
            raise RuntimeError("OnlineDeployment was never start()-ed")
        self._train_thread.join(timeout)
        if self._train_thread.is_alive():
            raise TimeoutError(
                f"training run still live after {timeout}s")
        if self._train_error is not None:
            raise self._train_error
        return self._fitted

    def stop(self, drain_timeout: Optional[float] = 30.0):
        """Wind the whole graph down: end the stream (the horizon loop
        finishes its current horizon and returns), join training, stop
        the engine supervisor, and drain the serving engine.  Returns the
        fitted model (None if training failed before fitting)."""
        self.source.stop()
        fitted = None
        if self._train_thread is not None:
            try:
                fitted = self.join()
            except TimeoutError:
                raise
            except BaseException:
                logger.warning("online deployment stopped after a failed "
                               "training run", exc_info=True)
        if self.supervisor is not None:
            self.supervisor.stop()
        eng, _ = self._current()
        if eng.dead is None:
            eng.drain(timeout=drain_timeout)
        self._publish_freshness()
        return fitted

    def __enter__(self) -> "OnlineDeployment":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the serving surface -------------------------------------------------
    def feed(self, x, y) -> int:
        """Feed served traffic (or any labeled rows) back into the
        stream: rows are stamped NOW — their freshness clock starts at
        this call — and spliced ahead of base-stream rows in the next
        horizon read."""
        return self.source.feed(x, y)

    def submit(self, prompt, num_steps: int, **kw):
        """Submit one request to the CURRENT engine; returns
        ``(handle, generation)`` — the attribution contract: the request
        runs on exactly the engine generation it was submitted to (an
        in-between swap drains the old engine, it never kills it)."""
        eng, gen = self._current()
        return eng.submit(prompt, num_steps, **kw), gen

    def serve(self, prompts, num_steps: int = 1, retries: int = 3,
              retry_wait_s: float = 2.0, **kw):
        """Serve a batch of prompts against the live deployment; returns
        ``(rows, generations)`` — one ``generate``-shaped row and one
        serve-generation tag per prompt.

        Inline engines (never ``start()``-ed) are pumped on this thread —
        the deterministic, sleep-free path.  Live engines resolve through
        their decode loop.  A request failed by an engine death
        (:class:`EngineDead`) is resubmitted to the replacement engine up
        to ``retries`` times (deterministic seeds make the retry
        idempotent — same tokens, new generation), waiting up to
        ``retry_wait_s`` for the supervisor's swap; requests are lost
        only when every retry is exhausted, and then loudly."""
        prompts = [np.asarray(p, np.int32) for p in prompts]
        rows: List[Optional[np.ndarray]] = [None] * len(prompts)
        gens: List[Optional[int]] = [None] * len(prompts)
        outstanding = list(range(len(prompts)))
        for attempt in range(int(retries) + 1):
            eng, gen = self._current()
            if eng.dead is not None:
                eng = self._await_replacement(eng, retry_wait_s)
                eng, gen = self._current()
            handles = []
            for i in outstanding:
                handles.append((i, eng.submit(prompts[i], num_steps,
                                              **kw)))
            self._pump(eng, [h for _, h in handles])
            failed: List[int] = []
            for i, h in handles:
                try:
                    rows[i] = h.result()
                    gens[i] = gen
                except EngineDead:
                    failed.append(i)
            outstanding = failed
            if not outstanding:
                return rows, gens
        raise EngineDead(
            f"{len(outstanding)} request(s) lost after {retries} "
            f"engine-death retries")

    def _pump(self, eng: ServingEngine, handles) -> None:
        """Drive an inline engine to completion of ``handles`` on the
        calling thread (live engines return immediately — their decode
        loop owns the stepping)."""
        if eng._thread is not None or eng.dead is not None:
            return
        # generous bound: every handle's full prompt+decode budget plus
        # queue depth, so a stuck request raises instead of spinning
        budget = sum(len(h.prompt) + h.num_steps + 2 for h in handles)
        budget = (budget + 16) * max(1, len(handles))
        steps = 0
        while any(not h.done for h in handles):
            eng.step()
            steps += 1
            if eng.dead is not None:
                return
            if steps > budget:
                raise RuntimeError(
                    f"inline serve exceeded its step budget ({budget}) "
                    f"with requests still pending")

    def _await_replacement(self, dead_eng: ServingEngine,
                           wait_s: float) -> ServingEngine:
        """Wait (bounded) for the supervisor to swap a replacement in
        after ``dead_eng`` died."""
        deadline = time.monotonic() + float(wait_s)
        while time.monotonic() < deadline:
            eng, _ = self._current()
            if eng is not dead_eng and eng.dead is None:
                return eng
            time.sleep(0.01)
        eng, _ = self._current()
        if eng.dead is not None:
            raise EngineDead(
                "no live replacement engine arrived within "
                f"{wait_s}s of the kill") from eng.dead
        return eng

    # -- blue/green ----------------------------------------------------------
    def blue_green_swap(self, pull: bool = True,
                        drain_timeout: Optional[float] = 30.0
                        ) -> Dict[str, Any]:
        """Serve generation *g* while *g+1* warms, then swap atomically.

        The replacement is ``respawn_clone()`` (same weights/knobs/
        attachment — the PR 8 restart path), optionally hot-pulled to the
        freshest center BEFORE warmup, then ``warmup()``-ed so its first
        live step pays zero jit.  The swap itself is one assignment
        through the deployment's ``engine`` setter — submissions observe
        either (old, g) or (new, g+1), never a torn pair — and the old
        engine drains: every request in flight at the swap finishes on
        the generation that admitted it."""
        old, old_gen = self._current()
        new = old.respawn_clone()
        if pull and new._ps_addr is not None:
            # warm g+1 with the live center (best-effort, same contract
            # as any hot reload — a dead PS leaves the cloned weights)
            new._pull_weights()
        new.warmup()
        was_live = old._thread is not None
        if was_live:
            new.start()
        self.engine = new  # the atomic generation bump
        t0 = time.monotonic()
        drained = old.drain(timeout=drain_timeout)
        with self._lock:
            record = self.swaps[-1]
        record.update({"blue_green": True, "pulled": bool(
            pull and new._ps_addr is not None and
            new.stats["reloads"] > 0),
            "old_drained_clean": bool(drained),
            "drain_ms": round((time.monotonic() - t0) * 1e3, 1)})
        return record

    # -- chaos ---------------------------------------------------------------
    def kill_engine(self, reason: str = "chaos: engine killed") -> None:
        """Chaos hook: declare the current engine dead (every in-flight
        handle fails with :class:`EngineDead`).  With ``supervise=True``
        the :class:`EngineSupervisor` respawns and swaps a warmed clone
        in; :meth:`serve` resubmits its failed requests there."""
        eng, _ = self._current()
        eng.declare_dead(reason)

    def kill_ps_shard(self, j: int = 0) -> None:
        """Chaos hook: crash PS shard ``j`` through the training run's
        ``ShardSupervisor`` (same-address respawn from the journal).
        Requires ``recovery=True`` on the trainer."""
        sup = getattr(self.trainer, "_ps_supervisor", None)
        if sup is None:
            raise RuntimeError(
                "no ShardSupervisor: construct the trainer with "
                "recovery=True to make the PS killable")
        sup.kill_shard(j)

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """One merged deployment snapshot: freshness percentiles, serve
        generation + swap records, engine reload/request counters, and —
        once training ended — the trainer's stream/elastic stats."""
        with self._lock:
            eng, gen = self._engine, self.generation
            swaps = [dict(s) for s in self.swaps]
        out: Dict[str, Any] = {"generation": gen,
                               "swaps": swaps,
                               "rows_fed_back":
                                   self.source.rows_fed_back,
                               "ps_addr": self.ps_addr,
                               "training_done": self.done}
        out.update(self.tracker.stats())
        for k in ("reloads", "reload_failures", "center_generation",
                  "weight_reloads", "requests_submitted",
                  "requests_completed", "requests_failed",
                  "requests_rejected", "decode_steps",
                  "tokens_generated"):
            out[f"engine_{k}"] = eng.stats[k]
        if self.supervisor is not None:
            out["engine_recoveries"] = [dict(r) for r in
                                        self.supervisor.recoveries]
        if self.done:
            out["stream_stats"] = dict(
                getattr(self.trainer, "stream_stats", {}) or {})
            out["elastic_stats"] = {
                k: v for k, v in
                (getattr(self.trainer, "elastic_stats", {}) or {}).items()
                if k in ("respawns", "leases_reassigned")}
        return out
