"""Metric evaluation over predicted datasets (reference:
``distkeras/evaluators.py`` — SURVEY.md §2.1 row 20).

``AccuracyEvaluator.evaluate(dataset)`` computes the fraction of rows where
the predicted class index equals the label — same contract as the reference's
Spark aggregation, executed as one vectorized numpy pass.
"""

from __future__ import annotations

import numpy as np

from .data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    def __init__(self, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        pred, label = _pred_and_label(dataset, self.prediction_col,
                                      self.label_col)
        return float(np.mean(pred == label))


def _labels_1d(label: np.ndarray) -> np.ndarray:
    if label.ndim > 1 and label.shape[-1] > 1:  # one-hot labels
        label = np.argmax(label, axis=-1)
    return label.reshape(-1).astype(np.int64)


def _pred_and_label(dataset: Dataset, prediction_col: str, label_col: str):
    pred = np.asarray(dataset[prediction_col]).reshape(-1)
    label = _labels_1d(np.asarray(dataset[label_col]))
    if np.issubdtype(pred.dtype, np.floating):
        # prediction_col must hold class indices; round-to-nearest tolerates
        # float storage of integers while NaN/inf (undefined as a class)
        # fail loudly instead of casting to a platform-defined int64
        if not np.isfinite(pred).all():
            raise ValueError(
                f"column {prediction_col!r} contains NaN/inf — expected "
                "integer class indices (run LabelIndexTransformer first)")
        pred = np.rint(pred)
    return pred.astype(np.int64), label


class F1Evaluator(Evaluator):
    """Precision / recall / F1 over predicted class indices (extra over the
    reference, which ships accuracy only).

    ``average``: ``"binary"`` (score class ``positive_label``), ``"macro"``
    (unweighted mean of per-class scores over classes present in labels or
    predictions), or ``"micro"`` (global counts — equals accuracy for
    single-label classification).  ``metric`` picks ``"f1"`` (default),
    ``"precision"`` or ``"recall"``; empty denominators score 0.
    """

    def __init__(self, average: str = "binary", metric: str = "f1",
                 positive_label: int = 1,
                 prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        if average not in ("binary", "macro", "micro"):
            raise ValueError(f"unknown average {average!r}")
        if metric not in ("f1", "precision", "recall"):
            raise ValueError(f"unknown metric {metric!r}")
        self.average = average
        self.metric = metric
        self.positive_label = int(positive_label)
        self.prediction_col = prediction_col
        self.label_col = label_col

    @staticmethod
    def _scores(tp, fp, fn):
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        f1 = (2 * prec * rec / (prec + rec)) if prec + rec else 0.0
        return {"precision": prec, "recall": rec, "f1": f1}

    def evaluate(self, dataset: Dataset) -> float:
        pred, label = _pred_and_label(dataset, self.prediction_col,
                                      self.label_col)
        if self.average == "binary":
            classes = [self.positive_label]
        else:
            classes = np.union1d(np.unique(pred), np.unique(label))
        per_class = []
        total = np.zeros(3)
        for c in classes:
            tp = float(np.sum((pred == c) & (label == c)))
            fp = float(np.sum((pred == c) & (label != c)))
            fn = float(np.sum((pred != c) & (label == c)))
            total += (tp, fp, fn)
            per_class.append(self._scores(tp, fp, fn)[self.metric])
        if self.average == "micro":
            return float(self._scores(*total)[self.metric])
        return float(np.mean(per_class))


class TopKAccuracyEvaluator(Evaluator):
    """Fraction of rows whose label is in the top-k of the predicted
    probability/logit vector (``prediction`` column, not the argmax index)."""

    def __init__(self, k: int = 5, prediction_col: str = "prediction",
                 label_col: str = "label"):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        probs = np.asarray(dataset[self.prediction_col])
        label = _labels_1d(np.asarray(dataset[self.label_col]))
        if probs.ndim != 2:
            raise ValueError(
                f"column {self.prediction_col!r} must be (N, num_classes) "
                f"probability/logit vectors, got shape {probs.shape}")
        k = min(self.k, probs.shape[-1])
        topk = np.argpartition(-probs, k - 1, axis=-1)[:, :k]
        return float(np.mean((topk == label[:, None]).any(axis=1)))


class LossEvaluator(Evaluator):
    """Mean loss over a predicted dataset (extra over reference — cheap and
    useful for parity tests)."""

    def __init__(self, loss: str = "categorical_crossentropy",
                 prediction_col: str = "prediction",
                 label_col: str = "label_encoded"):
        from .core.losses import get_loss
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        import jax.numpy as jnp
        pred = jnp.asarray(dataset[self.prediction_col])
        label = jnp.asarray(dataset[self.label_col])
        return float(self.loss_fn(label, pred))


class AUCEvaluator(Evaluator):
    """Area under the ROC curve for binary tasks (extra over reference —
    the Higgs workload upstream reports accuracy only, but AUC is the
    standard metric for that dataset).

    ``prediction`` column holds a positive-class score per row: either a
    (N,) score/probability vector, a (N, 1) column, or (N, 2) class
    probabilities (column 1 is used).  Labels are 0/1 (or one-hot).
    Computed by the rank statistic (Mann-Whitney U), ties handled by
    midranks — exact for any score distribution, O(N log N).
    """

    def __init__(self, prediction_col: str = "prediction",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        score = np.asarray(dataset[self.prediction_col], np.float64)
        if score.ndim == 2 and score.shape[1] == 2:
            score = score[:, 1]
        score = score.reshape(-1)
        label = _labels_1d(np.asarray(dataset[self.label_col]))
        if score.shape[0] != label.shape[0]:
            raise ValueError(
                f"prediction/label length mismatch: {score.shape[0]} vs "
                f"{label.shape[0]}")
        classes = np.unique(label)
        if not np.isin(classes, (0, 1)).all():
            raise ValueError(
                f"AUC is binary: labels must be 0/1, got classes {classes}")
        pos = label == 1
        n_pos = int(pos.sum())
        n_neg = label.shape[0] - n_pos
        if n_pos == 0 or n_neg == 0:
            raise ValueError("AUC undefined: need both classes present")
        # midranks (average rank within tied groups), vectorized: group
        # starts where the sorted score changes; each element's midrank is
        # the mean of its group's first and last 1-based positions
        order = np.argsort(score, kind="mergesort")
        sorted_scores = score[order]
        n = len(sorted_scores)
        new_group = np.empty(n, bool)
        new_group[0] = True
        np.not_equal(sorted_scores[1:], sorted_scores[:-1],
                     out=new_group[1:])
        starts = np.nonzero(new_group)[0]
        ends = np.append(starts[1:], n) - 1
        group_of = np.cumsum(new_group) - 1
        midrank = 0.5 * (starts + ends) + 1.0
        ranks = np.empty_like(score)
        ranks[order] = midrank[group_of]
        u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
        return float(u / (n_pos * n_neg))
