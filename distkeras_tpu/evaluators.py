"""Metric evaluation over predicted datasets (reference:
``distkeras/evaluators.py`` — SURVEY.md §2.1 row 20).

``AccuracyEvaluator.evaluate(dataset)`` computes the fraction of rows where
the predicted class index equals the label — same contract as the reference's
Spark aggregation, executed as one vectorized numpy pass.
"""

from __future__ import annotations

import numpy as np

from .data.dataset import Dataset


class Evaluator:
    def evaluate(self, dataset: Dataset) -> float:  # pragma: no cover
        raise NotImplementedError


class AccuracyEvaluator(Evaluator):
    def __init__(self, prediction_col: str = "prediction_index",
                 label_col: str = "label"):
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        pred = np.asarray(dataset[self.prediction_col]).reshape(-1)
        label = np.asarray(dataset[self.label_col])
        if label.ndim > 1 and label.shape[-1] > 1:  # one-hot labels
            label = np.argmax(label, axis=-1)
        label = label.reshape(-1)
        return float(np.mean(pred == label))


class LossEvaluator(Evaluator):
    """Mean loss over a predicted dataset (extra over reference — cheap and
    useful for parity tests)."""

    def __init__(self, loss: str = "categorical_crossentropy",
                 prediction_col: str = "prediction",
                 label_col: str = "label_encoded"):
        from .core.losses import get_loss
        self.loss_fn = get_loss(loss)
        self.prediction_col = prediction_col
        self.label_col = label_col

    def evaluate(self, dataset: Dataset) -> float:
        import jax.numpy as jnp
        pred = jnp.asarray(dataset[self.prediction_col])
        label = jnp.asarray(dataset[self.label_col])
        return float(self.loss_fn(label, pred))
