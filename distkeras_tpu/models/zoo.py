"""Model zoo matching the reference example workloads (SURVEY.md §2.1 row 23,
``BASELINE.json.configs``): MNIST MLP, MNIST ConvNet, CIFAR-10 ConvNet, and
the ATLAS Higgs tabular MLP.  Architectures follow the reference notebooks'
shapes (Dense-500/Conv-32 scale models); exact layer dims are ours.
"""

from __future__ import annotations

from ..core import (Sequential, Dense, Conv2D, MaxPooling2D, Flatten, Reshape,
                    Dropout)
from ..core.layers import (Embedding, PositionalEmbedding, TransformerBlock,
                           LayerNormalization)


def mnist_mlp(compute_dtype: str = "bfloat16") -> Sequential:
    """MLP on flat 784-dim MNIST rows (reference ``examples/mnist.ipynb``
    MLP variant / workflow.ipynb-style two-hidden-layer net)."""
    return Sequential([
        Dense(500, activation="relu"),
        Dense(500, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,), compute_dtype=compute_dtype, name="mnist_mlp")


def mnist_convnet(compute_dtype: str = "bfloat16") -> Sequential:
    """ConvNet on 28x28x1 MNIST (the ADAG north-star benchmark model;
    reference ``examples/mnist.ipynb`` ConvNet)."""
    return Sequential([
        Reshape((28, 28, 1)),
        Conv2D(32, 3, activation="relu"),
        Conv2D(32, 3, activation="relu"),
        MaxPooling2D(2),
        Conv2D(64, 3, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(128, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,), compute_dtype=compute_dtype, name="mnist_convnet")


def digits_mlp(compute_dtype: str = "bfloat16") -> Sequential:
    """MLP on the REAL sklearn-digits workload (64-dim 8x8 images — see
    ``data.datasets.load_digits``): the accuracy-parity artifact's real-data
    model, sized down from ``mnist_mlp`` for the smaller input."""
    return Sequential([
        Dense(128, activation="relu"),
        Dense(128, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(64,), compute_dtype=compute_dtype, name="digits_mlp")


def digits_convnet(compute_dtype: str = "bfloat16") -> Sequential:
    """ConvNet on the REAL sklearn-digits workload: flat 64-dim rows
    reshaped to 8x8x1 through a small Conv2D stack — the conv analogue of
    ``digits_mlp`` so the real-pixel accuracy-parity gate covers the
    north-star MODEL FAMILY (MNIST ConvNet, SURVEY.md §6), not just an
    MLP.  'same' padding keeps the tiny 8x8 plane from vanishing before
    the pool."""
    return Sequential([
        Reshape((8, 8, 1)),
        Conv2D(16, 3, activation="relu", padding="same"),
        Conv2D(16, 3, activation="relu", padding="same"),
        MaxPooling2D(2),
        Conv2D(32, 3, activation="relu", padding="same"),
        MaxPooling2D(2),
        Flatten(),
        Dense(64, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(64,), compute_dtype=compute_dtype,
        name="digits_convnet")


def cifar10_convnet(compute_dtype: str = "bfloat16") -> Sequential:
    """Small ConvNet on 32x32x3 CIFAR-10 (reference DOWNPOUR config)."""
    return Sequential([
        Reshape((32, 32, 3)),
        Conv2D(32, 3, activation="relu"),
        Conv2D(32, 3, activation="relu"),
        MaxPooling2D(2),
        Conv2D(64, 3, activation="relu"),
        Conv2D(64, 3, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(256, activation="relu"),
        Dropout(0.5),
        Dense(10, activation="softmax"),
    ], input_shape=(3072,), compute_dtype=compute_dtype,
        name="cifar10_convnet")


def higgs_mlp(compute_dtype: str = "bfloat16") -> Sequential:
    """Tabular MLP for ATLAS Higgs signal/background (reference
    ``examples/workflow.ipynb``: Dense-500/relu stack, 2-way softmax)."""
    return Sequential([
        Dense(500, activation="relu"),
        Dense(500, activation="relu"),
        Dense(2, activation="softmax"),
    ], input_shape=(28,), compute_dtype=compute_dtype, name="higgs_mlp")


def transformer_lm(vocab_size: int = 256, seq_len: int = 128,
                   d_model: int = 128, num_heads: int = 4,
                   num_layers: int = 2, mlp_dim: int = 512,
                   dropout: float = 0.0, compute_dtype: str = "bfloat16",
                   attention_impl=None, num_kv_heads=None,
                   attention_window=None,
                   positional: str = "learned",
                   rope_theta: float = 10000.0,
                   rope_scale: float = 1.0) -> Sequential:
    """Decoder-only causal transformer LM — the long-context flagship.

    No reference counterpart (SURVEY.md §2.3: attention/sequence models are
    absent upstream); this model family exists so the framework's sequence-
    parallel path (ring attention over a 'seq' mesh axis) has a first-class
    workload.  Input: (seq_len,) int token ids; output: (seq_len, vocab)
    logits — train with loss="sparse_categorical_crossentropy_from_logits".
    """
    if positional not in ("learned", "rope"):
        raise ValueError(f"positional must be 'learned' or 'rope', got "
                         f"{positional!r}")
    rope = positional == "rope"
    layers = [Embedding(vocab_size, d_model)]
    if not rope:  # RoPE rotates q/k inside attention; no additive table
        layers.append(PositionalEmbedding(seq_len))
    for _ in range(num_layers):
        layers.append(TransformerBlock(
            num_heads, d_model // num_heads, mlp_dim, dropout=dropout,
            causal=True, attention_impl=attention_impl,
            num_kv_heads=num_kv_heads, attention_window=attention_window,
            rope=rope, rope_theta=rope_theta, rope_scale=rope_scale))
    layers += [LayerNormalization(), Dense(vocab_size)]
    return Sequential(layers, input_shape=(seq_len,),
                      compute_dtype=compute_dtype, name="transformer_lm")
