"""Model zoo matching the reference example workloads (SURVEY.md §2.1 row 23,
``BASELINE.json.configs``): MNIST MLP, MNIST ConvNet, CIFAR-10 ConvNet, and
the ATLAS Higgs tabular MLP.  Architectures follow the reference notebooks'
shapes (Dense-500/Conv-32 scale models); exact layer dims are ours.
"""

from __future__ import annotations

from ..core import (Sequential, Dense, Conv2D, MaxPooling2D, Flatten, Reshape,
                    Dropout)


def mnist_mlp(compute_dtype: str = "bfloat16") -> Sequential:
    """MLP on flat 784-dim MNIST rows (reference ``examples/mnist.ipynb``
    MLP variant / workflow.ipynb-style two-hidden-layer net)."""
    return Sequential([
        Dense(500, activation="relu"),
        Dense(500, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,), compute_dtype=compute_dtype, name="mnist_mlp")


def mnist_convnet(compute_dtype: str = "bfloat16") -> Sequential:
    """ConvNet on 28x28x1 MNIST (the ADAG north-star benchmark model;
    reference ``examples/mnist.ipynb`` ConvNet)."""
    return Sequential([
        Reshape((28, 28, 1)),
        Conv2D(32, 3, activation="relu"),
        Conv2D(32, 3, activation="relu"),
        MaxPooling2D(2),
        Conv2D(64, 3, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(128, activation="relu"),
        Dense(10, activation="softmax"),
    ], input_shape=(784,), compute_dtype=compute_dtype, name="mnist_convnet")


def cifar10_convnet(compute_dtype: str = "bfloat16") -> Sequential:
    """Small ConvNet on 32x32x3 CIFAR-10 (reference DOWNPOUR config)."""
    return Sequential([
        Reshape((32, 32, 3)),
        Conv2D(32, 3, activation="relu"),
        Conv2D(32, 3, activation="relu"),
        MaxPooling2D(2),
        Conv2D(64, 3, activation="relu"),
        Conv2D(64, 3, activation="relu"),
        MaxPooling2D(2),
        Flatten(),
        Dense(256, activation="relu"),
        Dropout(0.5),
        Dense(10, activation="softmax"),
    ], input_shape=(3072,), compute_dtype=compute_dtype,
        name="cifar10_convnet")


def higgs_mlp(compute_dtype: str = "bfloat16") -> Sequential:
    """Tabular MLP for ATLAS Higgs signal/background (reference
    ``examples/workflow.ipynb``: Dense-500/relu stack, 2-way softmax)."""
    return Sequential([
        Dense(500, activation="relu"),
        Dense(500, activation="relu"),
        Dense(2, activation="softmax"),
    ], input_shape=(28,), compute_dtype=compute_dtype, name="higgs_mlp")
