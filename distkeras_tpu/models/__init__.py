from .zoo import mnist_mlp, mnist_convnet, cifar10_convnet, higgs_mlp

__all__ = ["mnist_mlp", "mnist_convnet", "cifar10_convnet", "higgs_mlp"]
