from .zoo import (mnist_mlp, mnist_convnet, cifar10_convnet, higgs_mlp,
                  transformer_lm)

__all__ = ["mnist_mlp", "mnist_convnet", "cifar10_convnet", "higgs_mlp",
           "transformer_lm"]
