"""distkeras_tpu — a TPU-native distributed training framework with the
capability surface of dist-keras (Spark/parameter-server distributed Keras),
rebuilt on JAX/XLA: SPMD over a device mesh with ICI collectives instead of a
socket parameter-server star.  See SURVEY.md for the reference analysis and
README.md for the architecture.
"""

__version__ = "0.1.0"

from . import core, data, parallel
from .core import (Sequential, Dense, Conv2D, MaxPooling2D, Flatten, Reshape,
                   Activation, Dropout, BatchNormalization,
                   SGD, Adam, Adagrad, Adadelta, RMSprop)
from .core.model import FittedModel, serialize_model, deserialize_model
from .data import (Dataset, MinMaxTransformer, StandardScaleTransformer,
                   DenseTransformer, ReshapeTransformer, OneHotTransformer,
                   LabelIndexTransformer)
from .trainers import (Trainer, SingleTrainer, AveragingTrainer,
                       EnsembleTrainer, DistributedTrainer,
                       AsynchronousDistributedTrainer,
                       SynchronousDistributedTrainer,
                       ADAG, DOWNPOUR, AEASGD, EAMSGD, DynSGD)
from .predictors import Predictor, ModelPredictor
from . import serving
from .serving import (Draining, EngineDead, QueueFull, QuotaExceeded,
                      RequestHandle, ServingClient, ServingEngine,
                      ServingServer, TenantPolicy)
from . import router
from .router import ServingRouter
from .evaluators import (Evaluator, AccuracyEvaluator, AUCEvaluator,
                         F1Evaluator, LossEvaluator, TopKAccuracyEvaluator)
from . import utils
from . import networking
from . import streaming
from .streaming import StreamBuffer, StreamSource
from . import deployment_online
from .deployment_online import FreshnessTracker, OnlineDeployment
from . import workers
from . import ps_sharding
from . import parameter_servers
from . import resilience
from .ps_sharding import PSShardDown
from .resilience import (EngineSupervisor, FleetSupervisor, LeaseLedger,
                         RetryPolicy, ShardSupervisor, WorkerSupervisor)
from .networking import ChaosFault, ChaosProxy
from . import job_deployment
from . import checkpoint
from . import metrics
from .checkpoint import Checkpointer, OrbaxCheckpointer, make_checkpointer
from .metrics import MetricsLogger
