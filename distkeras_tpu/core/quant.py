"""Weight-only int8 quantization for the inference/serving path.

No reference counterpart (the reference serves full-precision Keras models;
SURVEY.md §2.1 rows 18/23) — this exists because TPU decode is
HBM-bandwidth-bound on *weight reads*: each generated token streams every
matmul kernel out of HBM once, so storing them int8 with a per-output-
channel f32 scale cuts that traffic (and the resident model footprint) 2×
vs bf16 / 4× vs f32, while activations, biases, norms, and embeddings stay
full precision (weight-only post-training quantization).

The mechanism is a pytree leaf, not a model rewrite: ``QuantizedTensor``
carries ``(int8 codes, f32 scale)`` and dequantizes inside ``astype`` —
the one method every matmul site in ``core/layers.py`` / ``core/decode.py``
already calls on its weight (``params["kernel"].astype(compute_dtype)``,
``_project``'s ``kernel.astype``).  Under jit, XLA fuses the
``codes.astype(f32) * scale`` dequant into the consuming matmul's operand
stream, so nothing dequantized is ever materialized in HBM.  Quantized
params therefore flow through the UNMODIFIED forward/decode code, jit,
and checkpointing (the leaf flattens to its two arrays).

Symmetric per-output-channel scheme: ``scale = max|w| / 127`` reduced over
all but the last axis (the output-features axis of every (in, out) kernel
and HWIO conv), ``codes = round(w / scale)``.  Training is untouched —
quantize AFTER training via ``FittedModel.quantize()`` /
``quantize_params``.
"""

from __future__ import annotations

from typing import Any, Set

import jax
import jax.numpy as jnp

#: matmul-kernel leaf names across Dense / Conv2D / MultiHeadAttention /
#: TransformerBlock (layers.py) — biases, norms, and embedding tables are
#: deliberately absent (tiny, or indexed rather than astype'd)
QUANT_KEYS: Set[str] = {"kernel", "wq", "wk", "wv", "wo",
                        "mlp_w1", "mlp_w2"}


class QuantizedTensor:
    """(int8 codes, f32 per-output-channel scale) posing as a weight array.

    ``astype`` is the whole contract: it returns the dequantized array in
    the requested dtype (f32 multiply first, then the cast — bf16-exact for
    the magnitudes weights live at).  ``shape``/``ndim`` mirror the logical
    array so shape-driven code keeps working.
    """

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):  # the logical (dequantized) dtype
        return jnp.float32

    def astype(self, dtype):
        return (self.q.astype(jnp.float32) * self.scale).astype(dtype)

    def __repr__(self):
        return f"QuantizedTensor(shape={tuple(self.shape)}, int8)"


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda t: ((t.q, t.scale), None),
    lambda _, xs: QuantizedTensor(*xs))


def quantize_tensor(w) -> QuantizedTensor:
    """Symmetric per-output-channel int8: scale over all but the last axis."""
    w = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q, scale)


def quantize_params(params: Any, keys: Set[str] = QUANT_KEYS) -> Any:
    """Replace every >=2-D matmul-kernel leaf (matched by name) with a
    ``QuantizedTensor``; everything else passes through untouched.  Works
    on any nesting of dicts/lists (the Sequential params layout)."""

    def walk(node):
        if isinstance(node, QuantizedTensor):
            return node  # idempotent: re-quantizing is a no-op
        if isinstance(node, dict):
            return {k: (quantize_tensor(v)
                        if k in keys and getattr(v, "ndim", 0) >= 2
                        and not isinstance(v, QuantizedTensor)
                        else walk(v))
                    for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return node

    return walk(params)


def dequantize_params(params: Any) -> Any:
    """Materialize every QuantizedTensor back to a plain f32 array (e.g.
    to resume training from a quantized artifact, accepting the rounding)."""
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32) if isinstance(x, QuantizedTensor)
        else x,
        params, is_leaf=lambda x: isinstance(x, QuantizedTensor))


def quantized_bytes(params: Any) -> int:
    """On-device bytes of the weight leaves (int8 codes + scales for
    quantized leaves, itemsize-true for the rest) — the footprint the
    transform is buying down."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total


# ---------------------------------------------------------------------------
# KV-cache quantization (the serving engine's int8 slot pool)
# ---------------------------------------------------------------------------
#
# Decode at high concurrency is HBM-bound on the KV pool the same way it is
# on weights: every step streams every slot's cached k/v.  Storing entries
# int8 with a per-(row, slot, head) f32 scale roughly halves slot bytes vs
# bf16 (4× vs f32), which at fixed pool HBM doubles ``num_slots`` — the
# concurrent-user capacity lever.  Quantization happens at WRITE time (one
# scale per cache entry, reduced over head_dim); the dequant multiply sits
# inside the jitted attention read, where XLA fuses it into the score/value
# matmuls — nothing dequantized is ever materialized in HBM.

def quantize_kv(x):
    """(…, head_dim) k/v entries → ``(int8 codes, f32 scales)`` with one
    symmetric scale per entry (amax over the trailing head_dim axis).
    Zero entries (never-written cache slots) keep scale 0, so they
    dequantize back to exact zeros."""
    xf = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 0.0)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype):
    """Codes + per-entry scales back to a dense array in ``dtype`` (the
    attention read; fused into the consuming matmul under jit)."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def kv_cache_bytes(caches: Any) -> int:
    """On-device bytes of a KV cache/pool pytree (codes + scales for int8
    pools, itemsize-true otherwise) — the byte-accounting behind the
    ``serving_quant_capacity_slots`` bench field."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(caches):
        total += leaf.size * jnp.dtype(leaf.dtype).itemsize
    return total


def kv_block_bytes(caches: Any, block_size: int) -> int:
    """Bytes ONE ``block_size``-token block costs across all layers of a
    paged arena (``core.decode.init_paged_arena`` — flat (A, ...) leaves,
    codes + scales included for int8 arenas).  ``kv_cache_bytes(arena) ==
    kv_block_bytes(arena, bs) × (num_blocks + 1)`` by construction; the
    per-block figure is what the paged capacity math
    (``serving_paged_capacity_slots``) and TUNING.md's fragmentation-vs-
    gather-overhead guidance reason in."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(caches):
        per_slot = (leaf.size // leaf.shape[0]) \
            * jnp.dtype(leaf.dtype).itemsize
        total += per_slot * int(block_size)
    return total
