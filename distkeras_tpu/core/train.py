"""Pure train-step construction.

The reference's per-batch hot loop is Keras ``train_on_batch`` inside
``distkeras/workers.py :: SequentialWorker.train`` (SURVEY.md §3.2).  Here the
equivalent is a pure function ``(params, opt_state, batch, rng) -> (params,
opt_state, loss)`` built once per (model, loss, optimizer) triple and jitted,
plus a ``lax.scan`` runner that executes a whole epoch of minibatches inside a
single XLA program — no per-batch Python dispatch, which is where the 8×+
throughput over the reference comes from.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .losses import get_loss, per_example
from .model import Sequential
from . import optimizers as opt_lib


class TrainState(NamedTuple):
    """Carried training state — a flat NamedTuple so it scans/shards cleanly."""
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar


def make_loss_fn(model: Sequential, loss) -> Callable:
    """(params, x, y, rng) -> (loss, stats_aux) — stats_aux is the
    ``{layer_index: new_stats}`` dict of EMA-updated BatchNorm running stats
    (empty for stat-free models)."""
    loss_fn = get_loss(loss)

    def compute(params, x, y, rng):
        stats: dict = {}
        pred = model.apply(params, x, train=True, rng=rng, stats_out=stats)
        return loss_fn(y, pred), stats

    return compute


def make_masked_loss_fn(model: Sequential, loss) -> Callable:
    """(params, x, y, w, rng[, seg]) -> (masked-mean loss, stats_aux).

    ``w`` is a per-example weight vector (1 real, 0 padding): the loss is
    Σ wᵢ·lossᵢ / max(Σ w, 1), so padded examples contribute exactly zero to
    value and gradient (``shape_epoch_data`` pads the tail round by wrapping
    real rows, keeping BatchNorm batch statistics sane).  ``seg`` (optional
    trailing arg): per-row segment ids for sequence packing, threaded into
    the forward (``data/packing.py``)."""
    per_ex = per_example(get_loss(loss))

    def compute(params, x, y, w, rng, seg=None):
        stats: dict = {}
        kw = {"segment_ids": seg} if seg is not None else {}
        pred = model.apply(params, x, train=True, rng=rng, stats_out=stats,
                           **kw)
        losses = per_ex(y, pred)
        w = w.astype(jnp.float32)
        return jnp.sum(losses * w) / jnp.maximum(jnp.sum(w), 1.0), stats

    return compute


def make_masked_step(model: Sequential, loss,
                     tx: optax.GradientTransformation) -> Callable:
    """The one masked minibatch step shared by all three engines
    (``make_epoch_runner``, the SPMD window scan, the host-PS worker window).

    (params, opt_state, x, y, w, rng[, seg]) -> (params, opt_state, loss,
    wsum) — ``seg`` as in ``make_masked_loss_fn``.

    A fully-padded batch (wsum == 0) is a TRUE no-op: the masked loss gives
    zero gradient, but e.g. Adam still moves parameters on a zero gradient
    (decayed momentum over sqrt(v)), so the whole update — params, optimizer
    state, BatchNorm stats merge — is gated out with ``where`` in that case.
    """
    compute = make_masked_loss_fn(model, loss)

    def step(params, opt_state, x, y, w, rng, seg=None):
        (l, stats), grads = jax.value_and_grad(compute, has_aux=True)(
            params, x, y, w, rng, seg)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params = Sequential.merge_stats(new_params, stats)
        wsum = jnp.sum(w.astype(jnp.float32))
        keep = wsum > 0.0
        pick = lambda new, old: jax.tree_util.tree_map(
            lambda a, b: jnp.where(keep, a, b), new, old)
        return pick(new_params, params), pick(new_opt, opt_state), l, wsum

    return step


def make_train_step(model: Sequential, loss, tx: optax.GradientTransformation,
                    ) -> Callable:
    """Single-device SGD step: grad + optax update. Pure; jit at call site."""
    compute = make_loss_fn(model, loss)

    def step(state: TrainState, batch, rng) -> Tuple[TrainState, jnp.ndarray]:
        x, y = batch
        (loss_val, stats), grads = jax.value_and_grad(compute, has_aux=True)(
            state.params, x, y, rng)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        params = Sequential.merge_stats(params, stats)
        return TrainState(params, opt_state, state.step + 1), loss_val

    return step


def make_epoch_runner(model: Sequential, loss, tx,
                      packed: bool = False) -> Callable:
    """Scan stacked batch arrays through train steps inside one XLA program.

    ``xb``/``yb``/``mb`` have shape (num_batches, batch, ...); ``mb`` is the
    per-example real/padding mask (``batch_epoch_data``) so the tail batch
    is padded+masked instead of dropped.  Returns (state, per-batch losses);
    each loss is the exact mean over that batch's real examples.

    ``packed=True`` (sequence packing, ``data/packing.py``): the epoch
    additionally scans a stacked ``sb`` segment-ids array —
    ``epoch(state, xb, yb, sb, mb, rng)`` — threaded into the shared
    masked step's forward; use a ``*_masked`` loss so cross-document
    label -1 positions drop out.
    """
    step = make_masked_step(model, loss, tx)

    def epoch(state: TrainState, xb, yb, *rest):
        (sb, mb, rng) = rest if packed else (None,) + rest

        def body(carry, inp):
            st, key = carry
            x, y, seg, w = inp if packed else inp[:2] + (None,) + inp[2:]
            key, sub = jax.random.split(key)
            params, opt_state, l, _ = step(st.params, st.opt_state, x, y, w,
                                           sub, seg)
            st = TrainState(params, opt_state, st.step + 1)
            return (st, key), l

        xs = (xb, yb, sb, mb) if packed else (xb, yb, mb)
        (state, _), losses = jax.lax.scan(body, (state, rng), xs)
        return state, losses

    return jax.jit(epoch)


def batch_epoch_arrays(batch_size: int, *arrays):
    """Stack flat epoch arrays into (num_batches, batch, ...) + mask,
    wrap-padding the tail batch instead of dropping it.  All arrays share
    one row order; returns ``(*stacked, mask, num_batches)``."""
    n_rows = len(arrays[0])
    if n_rows == 0:
        raise ValueError("empty dataset")
    if any(len(a) != n_rows for a in arrays):
        raise ValueError("epoch arrays must share their row count")
    nb = -(-n_rows // batch_size)  # ceil: pad up, never drop
    rows = nb * batch_size
    idx = np.arange(rows) % n_rows
    mask = (np.arange(rows) < n_rows).astype(np.float32)
    shape = (nb, batch_size)
    stacked = tuple(np.asarray(a)[idx].reshape(shape + np.asarray(a).shape[1:])
                    for a in arrays)
    return stacked + (mask.reshape(shape), nb)


def batch_epoch_data(x: np.ndarray, y: np.ndarray, batch_size: int):
    """Stack a flat epoch into (num_batches, batch, ...) + mask, wrap-padding
    the tail batch instead of dropping it (single-device analogue of
    ``parallel.spmd.shape_epoch_data``)."""
    xb, yb, mask, nb = batch_epoch_arrays(batch_size, x, y)
    return xb, yb, mask, nb


def make_packed_epoch_runner(model: Sequential, loss, tx) -> Callable:
    """``make_epoch_runner(packed=True)`` — one scan body for both
    paths; see there."""
    return make_epoch_runner(model, loss, tx, packed=True)


def init_state(model: Sequential, rng, input_shape, optimizer,
               learning_rate=None, lr_schedule=None, total_steps=None,
               gradient_accumulation: int = 1,
               gradient_clip_norm=None
               ) -> Tuple[TrainState, optax.GradientTransformation]:
    """Initialize params + optimizer state for a model."""
    params = model.init(rng, input_shape)
    tx, opt_state = opt_lib.build(optimizer, params, learning_rate,
                                  lr_schedule, total_steps,
                                  gradient_accumulation,
                                  gradient_clip_norm)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), tx
