"""Pure train-step construction.

The reference's per-batch hot loop is Keras ``train_on_batch`` inside
``distkeras/workers.py :: SequentialWorker.train`` (SURVEY.md §3.2).  Here the
equivalent is a pure function ``(params, opt_state, batch, rng) -> (params,
opt_state, loss)`` built once per (model, loss, optimizer) triple and jitted,
plus a ``lax.scan`` runner that executes a whole epoch of minibatches inside a
single XLA program — no per-batch Python dispatch, which is where the 8×+
throughput over the reference comes from.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax

from .losses import get_loss
from .model import Sequential
from . import optimizers as opt_lib


class TrainState(NamedTuple):
    """Carried training state — a flat NamedTuple so it scans/shards cleanly."""
    params: Any
    opt_state: Any
    step: jnp.ndarray  # int32 scalar


def make_loss_fn(model: Sequential, loss) -> Callable:
    """(params, x, y, rng) -> (loss, stats_aux) — stats_aux is the
    ``{layer_index: new_stats}`` dict of EMA-updated BatchNorm running stats
    (empty for stat-free models)."""
    loss_fn = get_loss(loss)

    def compute(params, x, y, rng):
        stats: dict = {}
        pred = model.apply(params, x, train=True, rng=rng, stats_out=stats)
        return loss_fn(y, pred), stats

    return compute


def make_train_step(model: Sequential, loss, tx: optax.GradientTransformation,
                    ) -> Callable:
    """Single-device SGD step: grad + optax update. Pure; jit at call site."""
    compute = make_loss_fn(model, loss)

    def step(state: TrainState, batch, rng) -> Tuple[TrainState, jnp.ndarray]:
        x, y = batch
        (loss_val, stats), grads = jax.value_and_grad(compute, has_aux=True)(
            state.params, x, y, rng)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        params = Sequential.merge_stats(params, stats)
        return TrainState(params, opt_state, state.step + 1), loss_val

    return step


def make_epoch_runner(model: Sequential, loss, tx) -> Callable:
    """Scan a stacked batch array through train steps inside one XLA program.

    ``xs`` has shape (num_batches, batch, ...) for both features and labels.
    Returns (state, per-batch losses).
    """
    step = make_train_step(model, loss, tx)

    def epoch(state: TrainState, xb, yb, rng):
        def body(carry, inp):
            st, key = carry
            key, sub = jax.random.split(key)
            st, l = step(st, (inp[0], inp[1]), sub)
            return (st, key), l

        (state, _), losses = jax.lax.scan(body, (state, rng), (xb, yb))
        return state, losses

    return jax.jit(epoch)


def init_state(model: Sequential, rng, input_shape, optimizer,
               learning_rate=None) -> Tuple[TrainState, optax.GradientTransformation]:
    """Initialize params + optimizer state for a model."""
    params = model.init(rng, input_shape)
    tx, opt_state = opt_lib.build(optimizer, params, learning_rate)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), tx
