"""Layer system for distkeras_tpu.

TPU-first design notes
----------------------
Layers are *declarative specs*: lightweight Python objects holding only static
configuration (shapes, strides, activation names).  Parameters live outside the
layer in a pytree, so the whole forward pass is a pure function
``apply(params, x)`` that JAX can trace once and XLA can fuse aggressively.

This replaces the reference's reliance on Keras layer objects with mutable
weights (reference: ``distkeras/utils.py :: serialize_keras_model`` pickles a
Keras model's config + weights; here the spec *is* the config and the params
pytree *is* the weights).

All matmuls/convs run in a configurable ``compute_dtype`` (default bfloat16 on
TPU) with float32 parameters and float32 accumulation via
``preferred_element_type`` — this keeps the MXU fed without fp32 conversion
costs on the HBM side.  (Convs route through ``_conv_f32_acc``: jax 0.9's
conv transpose rule can't differentiate the upcast, so the f32-accumulating
conv carries a custom VJP — don't add ``preferred_element_type`` to a conv
call directly.)
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Any  # per-layer params: dict of arrays (possibly empty)

_ACTIVATIONS = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
    "softplus": jax.nn.softplus,
}


def get_activation(name: Optional[str]):
    if name is None:
        return _ACTIVATIONS["linear"]
    if callable(name):
        return name
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None


def _apply_activation(name, x):
    # softmax-family must run in f32 for numerical stability under bf16 compute.
    if name in ("softmax", "log_softmax", "sigmoid"):
        return get_activation(name)(x.astype(jnp.float32))
    return get_activation(name)(x)


# ---------------------------------------------------------------------------
# initializers (Keras-compatible names so serialized configs round-trip)
# ---------------------------------------------------------------------------

def _fans(shape: Sequence[int]) -> Tuple[int, int]:
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: (kh, kw, cin, cout)
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


def init_weight(rng, shape, scheme: str = "glorot_uniform", dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    if scheme == "glorot_uniform":
        limit = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if scheme == "glorot_normal":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(rng, shape, dtype)
    if scheme == "he_uniform":
        limit = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if scheme == "he_normal":
        std = math.sqrt(2.0 / fan_in)
        return std * jax.random.normal(rng, shape, dtype)
    if scheme == "zeros":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    raise ValueError(f"Unknown initializer {scheme!r}")


# ---------------------------------------------------------------------------
# Layer base
# ---------------------------------------------------------------------------

class Layer:
    """Base layer spec.

    Subclasses implement:
      - ``init(rng, in_shape) -> (params, out_shape)`` where shapes exclude the
        leading batch dim;
      - ``apply(params, x, *, compute_dtype, train, rng) -> y``.
    """

    #: class-level registry name (set via __init_subclass__)
    kind: str = "Layer"

    _REGISTRY: Dict[str, type] = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        cls.kind = cls.__name__
        Layer._REGISTRY[cls.__name__] = cls

    # -- config (serialization) --------------------------------------------
    def get_config(self) -> Dict[str, Any]:
        cfg = {k: v for k, v in self.__dict__.items() if not k.startswith("_")}
        cfg["kind"] = self.kind
        return cfg

    @staticmethod
    def from_config(cfg: Dict[str, Any]) -> "Layer":
        cfg = dict(cfg)
        kind = cfg.pop("kind")
        cls = Layer._REGISTRY[kind]
        obj = cls.__new__(cls)
        # JSON round-trips tuples (kernel_size, strides, target_shape, ...)
        # to lists; shape fields must come back as tuples.
        obj.__dict__.update({k: tuple(v) if isinstance(v, list) else v
                             for k, v in cfg.items()})
        return obj

    # -- shape/params -------------------------------------------------------
    def init(self, rng, in_shape):  # pragma: no cover - abstract
        raise NotImplementedError

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):  # pragma: no cover - abstract
        raise NotImplementedError

    def __repr__(self):
        cfg = {k: v for k, v in self.get_config().items() if k != "kind"}
        args = ", ".join(f"{k}={v!r}" for k, v in cfg.items())
        return f"{self.kind}({args})"


# ---------------------------------------------------------------------------
# Core layers
# ---------------------------------------------------------------------------

class Dense(Layer):
    """Fully connected layer (reference models are MLP-heavy:
    SURVEY.md §2.1 row 23 — MNIST MLP, ATLAS Higgs tabular)."""

    def __init__(self, units: int, activation: Optional[str] = None,
                 use_bias: bool = True, kernel_init: str = "glorot_uniform"):
        self.units = int(units)
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_init = kernel_init

    def init(self, rng, in_shape):
        (d,) = in_shape[-1:]
        params = {"kernel": init_weight(rng, (d, self.units), self.kernel_init)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,), jnp.float32)
        return params, tuple(in_shape[:-1]) + (self.units,)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        k = params["kernel"].astype(compute_dtype)
        y = jax.lax.dot_general(
            x.astype(compute_dtype), k,
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if self.use_bias:
            y = y + params["bias"]
        return _apply_activation(self.activation, y)


def _conv_f32_acc(x, k, strides, padding):
    """Convolution with low-precision operands and a float32-accumulated
    *forward* output.

    jax 0.9's conv transpose rule rejects ``preferred_element_type``
    upcasting under grad, so the f32-accumulating forward gets a custom VJP
    that differentiates the plain same-dtype conv.  Gradient contract: the
    backward convs therefore run entirely in ``compute_dtype`` (the
    cotangent is rounded once to ``compute_dtype``; on TPU the MXU still
    accumulates partial products in f32 internally, with bf16 rounding at
    conv boundaries) — standard mixed-precision training behavior, but
    note it is *less* precise than Dense's grads, which keep
    ``preferred_element_type=f32`` end to end.
    """
    dn = ("NHWC", "HWIO", "NHWC")

    @jax.custom_vjp
    def conv(x, k):
        return jax.lax.conv_general_dilated(
            x, k, strides, padding, dimension_numbers=dn,
            preferred_element_type=jnp.float32)

    def fwd(x, k):
        return conv(x, k), (x, k)

    def bwd(res, g):
        x, k = res
        _, vjp = jax.vjp(
            lambda a, b: jax.lax.conv_general_dilated(
                a, b, strides, padding, dimension_numbers=dn), x, k)
        return vjp(g.astype(x.dtype))

    conv.defvjp(fwd, bwd)
    return conv(x, k)


class Conv2D(Layer):
    """2-D convolution, NHWC layout (TPU-native; XLA tiles it onto the MXU)."""

    def __init__(self, filters: int, kernel_size=3, strides=1,
                 padding: str = "SAME", activation: Optional[str] = None,
                 use_bias: bool = True, kernel_init: str = "he_normal"):
        self.filters = int(filters)
        self.kernel_size = tuple(np.broadcast_to(kernel_size, (2,)).tolist())
        self.strides = tuple(np.broadcast_to(strides, (2,)).tolist())
        self.padding = padding.upper()
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_init = kernel_init

    def init(self, rng, in_shape):
        h, w, cin = in_shape
        kh, kw = self.kernel_size
        params = {
            "kernel": init_weight(rng, (kh, kw, cin, self.filters),
                                  self.kernel_init)
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((self.filters,), jnp.float32)
        out = jax.eval_shape(
            lambda x, k: jax.lax.conv_general_dilated(
                x, k, self.strides, self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC")),
            jax.ShapeDtypeStruct((1, h, w, cin), jnp.float32),
            jax.ShapeDtypeStruct((kh, kw, cin, self.filters), jnp.float32),
        )
        return params, tuple(out.shape[1:])

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        y = _conv_f32_acc(x.astype(compute_dtype),
                          params["kernel"].astype(compute_dtype),
                          self.strides, self.padding)
        if self.use_bias:
            y = y + params["bias"]
        return _apply_activation(self.activation, y)


class MaxPooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "VALID"):
        self.pool_size = tuple(np.broadcast_to(pool_size, (2,)).tolist())
        self.strides = (tuple(np.broadcast_to(strides, (2,)).tolist())
                        if strides is not None else self.pool_size)
        self.padding = padding.upper()

    def init(self, rng, in_shape):
        h, w, c = in_shape
        out = jax.eval_shape(
            lambda x: self.apply({}, x, compute_dtype=jnp.float32),
            jax.ShapeDtypeStruct((1, h, w, c), jnp.float32))
        return {}, tuple(out.shape[1:])

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        dims = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        return jax.lax.reduce_window(
            x, -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else
            jnp.iinfo(x.dtype).min,
            jax.lax.max, dims, strides, self.padding)


class AveragePooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "VALID"):
        self.pool_size = tuple(np.broadcast_to(pool_size, (2,)).tolist())
        self.strides = (tuple(np.broadcast_to(strides, (2,)).tolist())
                        if strides is not None else self.pool_size)
        self.padding = padding.upper()

    def init(self, rng, in_shape):
        h, w, c = in_shape
        out = jax.eval_shape(
            lambda x: self.apply({}, x, compute_dtype=jnp.float32),
            jax.ShapeDtypeStruct((1, h, w, c), jnp.float32))
        return {}, tuple(out.shape[1:])

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        dims = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        summed = jax.lax.reduce_window(
            x, jnp.zeros((), x.dtype), jax.lax.add, dims, strides,
            self.padding)
        return summed / float(np.prod(self.pool_size))


class GlobalAveragePooling2D(Layer):
    def __init__(self):
        pass

    def init(self, rng, in_shape):
        return {}, (in_shape[-1],)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return jnp.mean(x, axis=(1, 2))


class Flatten(Layer):
    def __init__(self):
        pass

    def init(self, rng, in_shape):
        return {}, (int(np.prod(in_shape)),)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return x.reshape(x.shape[0], -1)


class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int]):
        self.target_shape = tuple(int(d) for d in target_shape)

    def init(self, rng, in_shape):
        if int(np.prod(in_shape)) != int(np.prod(self.target_shape)):
            raise ValueError(
                f"Cannot reshape {in_shape} to {self.target_shape}")
        return {}, self.target_shape

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return x.reshape((x.shape[0],) + self.target_shape)


class Activation(Layer):
    def __init__(self, activation: str):
        self.activation = activation

    def init(self, rng, in_shape):
        return {}, tuple(in_shape)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return _apply_activation(self.activation, x)


def _dropout(rng, rate: float, x, train: bool):
    """Inverted dropout; identity at inference (shared by Dropout and
    TransformerBlock so the semantics live in one place)."""
    if not train or rate <= 0.0:
        return x
    if rng is None:
        raise ValueError("Dropout in train mode requires an rng")
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


class Dropout(Layer):
    """Inverted dropout; identity at inference. Uses the functional rng threaded
    through ``Model.apply`` (no global RNG state — jit/scan friendly)."""

    def __init__(self, rate: float):
        self.rate = float(rate)

    def init(self, rng, in_shape):
        return {}, tuple(in_shape)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return _dropout(rng, self.rate, x, train)


class BatchNormalization(Layer):
    """Batch norm with functional running stats.

    The running (mean, var) live in the params pytree under ``"stats"``.
    Apply stays pure: in train mode the layer normalizes with *batch*
    statistics and, through ``apply_with_stats``, returns the EMA-updated
    running stats as aux; the train step merges them back into the params
    pytree after the optimizer update (``Sequential.apply(..., stats_out=)``
    collects them, ``model.merge_stats`` writes them).  The optimizer masks
    the ``"stats"`` subtree out, so stats are carried, never trained.
    """

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)

    def init(self, rng, in_shape):
        c = in_shape[-1]
        params = {
            "scale": jnp.ones((c,), jnp.float32),
            "offset": jnp.zeros((c,), jnp.float32),
            # stats are non-trained; optimizer masks them out (see Model)
            "stats": {
                "mean": jnp.zeros((c,), jnp.float32),
                "var": jnp.ones((c,), jnp.float32),
            },
        }
        return params, tuple(in_shape)

    def _norm(self, params, x, train: bool):
        """Returns (y, new_stats); new_stats is None in eval mode."""
        x32 = x.astype(jnp.float32)
        new_stats = None
        if train:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x32, axis=axes)
            var = jnp.var(x32, axis=axes)
            m = self.momentum
            new_stats = jax.lax.stop_gradient({
                "mean": m * params["stats"]["mean"] + (1.0 - m) * mean,
                "var": m * params["stats"]["var"] + (1.0 - m) * var,
            })
        else:
            mean = params["stats"]["mean"]
            var = params["stats"]["var"]
        y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
        y = y * params["scale"] + params["offset"]
        return y.astype(x.dtype), new_stats

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return self._norm(params, x, train)[0]

    def apply_with_stats(self, params, x, *, compute_dtype=jnp.bfloat16,
                         rng=None):
        """Train-mode forward that also returns the EMA-updated running
        stats (keras semantics: moving = momentum·moving + (1−momentum)·batch,
        biased batch variance)."""
        return self._norm(params, x, True)


class LayerNormalization(Layer):
    """Layer norm over the trailing dim, f32 arithmetic (bf16-safe)."""

    def __init__(self, epsilon: float = 1e-5):
        self.epsilon = float(epsilon)

    def init(self, rng, in_shape):
        c = in_shape[-1]
        return {"scale": jnp.ones((c,), jnp.float32),
                "offset": jnp.zeros((c,), jnp.float32)}, tuple(in_shape)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
        return (y * params["scale"] + params["offset"]).astype(x.dtype)


class PositionalEmbedding(Layer):
    """Learned additive positional embedding for (B, S, D) inputs."""

    def __init__(self, max_len: int):
        self.max_len = int(max_len)

    def init(self, rng, in_shape):
        s, d = in_shape
        if s > self.max_len:
            raise ValueError(f"sequence {s} exceeds max_len {self.max_len}")
        params = {"embedding": 0.02 * jax.random.normal(
            rng, (self.max_len, d), jnp.float32)}
        return params, tuple(in_shape)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        s = x.shape[1]
        return x + params["embedding"][:s].astype(x.dtype)


def _project(x, kernel, bias, compute_dtype):
    y = jax.lax.dot_general(
        x.astype(compute_dtype), kernel.astype(compute_dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + bias
    return y


def _validate_window(window: int, causal: bool) -> int:
    """Eager attention_window validation for the layers — delegates to the
    one shared rule in ``ops.attention.validate_window`` (which the ops
    re-apply at trace time)."""
    from ..ops.attention import validate_window
    return validate_window(window, causal)


class MultiHeadAttention(Layer):
    """Multi-head self-attention on (B, S, D) inputs.

    The score/softmax path runs through ``ops.attention`` (XLA fusion or the
    Pallas flash kernel on TPU).  No reference counterpart — part of the
    long-context layer (SURVEY.md §2.3 marks SP/attention absent upstream).

    ``num_kv_heads`` < ``num_heads`` gives grouped-query attention (GQA;
    ``num_kv_heads=1`` is multi-query): the k/v projections shrink to
    ``num_kv_heads * key_dim`` columns, cutting KV projection FLOPs/params
    and the decode-time KV cache by ``num_heads / num_kv_heads``.
    """

    #: class-level defaults so older serialized configs (which lack these
    #: fields; from_config bypasses __init__) deserialize as classic MHA
    num_kv_heads: Optional[int] = None  # None = same as num_heads
    attention_window: Optional[int] = None  # None = full causal context
    rope: bool = False  # rotary position embeddings on q/k
    rope_theta: float = 10000.0  # RoPE base (raise via ntk_theta to extend)
    rope_scale: float = 1.0      # linear position-interpolation factor

    def __init__(self, num_heads: int, key_dim: int, causal: bool = False,
                 use_bias: bool = True, attention_impl: Optional[str] = None,
                 num_kv_heads: Optional[int] = None,
                 attention_window: Optional[int] = None,
                 rope: bool = False, rope_theta: float = 10000.0,
                 rope_scale: float = 1.0):
        self.num_heads = int(num_heads)
        self.key_dim = int(key_dim)  # per-head dim
        self.causal = bool(causal)
        self.use_bias = bool(use_bias)
        self.attention_impl = attention_impl
        if num_kv_heads is not None:
            self.num_kv_heads = int(num_kv_heads)
            if self.num_heads % self.num_kv_heads:
                raise ValueError(
                    f"num_heads={self.num_heads} not divisible by "
                    f"num_kv_heads={self.num_kv_heads}")
        if attention_window is not None:
            self.attention_window = _validate_window(attention_window,
                                                     causal)
        if rope:
            from ..ops.rope import validate_rope_dim
            validate_rope_dim(self.key_dim)
            self.rope = True
        if rope_theta != 10000.0 or rope_scale != 1.0:
            if not rope:
                # the knobs only feed apply_rope; silently ignoring them
                # would hide a config mistake
                raise ValueError(
                    f"rope_theta={rope_theta}/rope_scale={rope_scale} set "
                    "but rope=False — pass rope=True to enable rotary "
                    "embeddings, or drop the knobs")
            from ..ops.rope import validate_rope_scaling
            self.rope_theta, self.rope_scale = validate_rope_scaling(
                rope_theta, rope_scale)

    def _kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    def init(self, rng, in_shape):
        s, d = in_shape
        inner = self.num_heads * self.key_dim
        inner_kv = self._kv_heads() * self.key_dim
        ks = jax.random.split(rng, 4)
        params = {
            "wq": init_weight(ks[0], (d, inner)),
            "wk": init_weight(ks[1], (d, inner_kv)),
            "wv": init_weight(ks[2], (d, inner_kv)),
            "wo": init_weight(ks[3], (inner, d)),
        }
        if self.use_bias:
            params.update(bq=jnp.zeros((inner,), jnp.float32),
                          bk=jnp.zeros((inner_kv,), jnp.float32),
                          bv=jnp.zeros((inner_kv,), jnp.float32),
                          bo=jnp.zeros((d,), jnp.float32))
        return params, tuple(in_shape)

    #: Sequential.apply threads a packed batch's segment ids to layers
    #: that declare this (see data/packing.py)
    takes_segment_ids = True

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None, segment_ids=None):
        from ..ops.attention import attention
        b, s, _ = x.shape
        dh = self.key_dim

        def proj(name, heads):
            bias = params.get("b" + name[1]) if self.use_bias else None
            y = _project(x, params[name], bias, compute_dtype)
            return y.astype(compute_dtype).reshape(b, s, heads, dh)

        q = proj("wq", self.num_heads)
        k = proj("wk", self._kv_heads())
        v = proj("wv", self._kv_heads())
        if self.rope:
            from ..ops.rope import apply_rope
            pos = jnp.arange(s)
            q = apply_rope(q, pos, self.rope_theta, self.rope_scale)
            k = apply_rope(k, pos, self.rope_theta, self.rope_scale)
        out = attention(q, k, v,
                        causal=self.causal, impl=self.attention_impl,
                        window=self.attention_window,
                        segment_ids=segment_ids)
        out = out.reshape(b, s, self.num_heads * dh)
        bias_o = params.get("bo") if self.use_bias else None
        return _project(out, params["wo"], bias_o, compute_dtype)


class TransformerBlock(Layer):
    """Pre-LN transformer block: LN → MHA → residual, LN → MLP → residual.

    Self-contained params (no nested Layer objects) so the spec stays
    JSON-serializable like every other layer.
    """

    #: class-level defaults mirror MultiHeadAttention (older configs)
    num_kv_heads: Optional[int] = None
    attention_window: Optional[int] = None
    rope: bool = False
    rope_theta: float = 10000.0
    rope_scale: float = 1.0

    def __init__(self, num_heads: int, key_dim: int, mlp_dim: int,
                 dropout: float = 0.0, causal: bool = False,
                 activation: str = "gelu",
                 attention_impl: Optional[str] = None,
                 num_kv_heads: Optional[int] = None,
                 attention_window: Optional[int] = None,
                 rope: bool = False, rope_theta: float = 10000.0,
                 rope_scale: float = 1.0):
        self.num_heads = int(num_heads)
        self.key_dim = int(key_dim)
        self.mlp_dim = int(mlp_dim)
        self.dropout = float(dropout)
        self.causal = bool(causal)
        self.activation = activation
        self.attention_impl = attention_impl
        if num_kv_heads is not None:
            self.num_kv_heads = int(num_kv_heads)
        if attention_window is not None:
            self.attention_window = _validate_window(attention_window,
                                                     causal)
        if rope:
            from ..ops.rope import validate_rope_dim
            validate_rope_dim(self.key_dim)  # eager, like MultiHeadAttention
            self.rope = True
        if rope_theta != 10000.0 or rope_scale != 1.0:
            if not rope:
                raise ValueError(
                    f"rope_theta={rope_theta}/rope_scale={rope_scale} set "
                    "but rope=False — pass rope=True to enable rotary "
                    "embeddings, or drop the knobs")
            from ..ops.rope import validate_rope_scaling
            self.rope_theta, self.rope_scale = validate_rope_scaling(
                rope_theta, rope_scale)

    def _mha(self) -> MultiHeadAttention:
        return MultiHeadAttention(self.num_heads, self.key_dim,
                                  causal=self.causal,
                                  attention_impl=self.attention_impl,
                                  num_kv_heads=self.num_kv_heads,
                                  attention_window=self.attention_window,
                                  rope=self.rope,
                                  rope_theta=self.rope_theta,
                                  rope_scale=self.rope_scale)

    def init(self, rng, in_shape):
        s, d = in_shape
        k_ln1, k_attn, k_ln2, k_m1, k_m2 = jax.random.split(rng, 5)
        ln = LayerNormalization()
        attn_params, _ = self._mha().init(k_attn, in_shape)
        params = {
            "ln1": ln.init(k_ln1, in_shape)[0],
            "attn": attn_params,
            "ln2": ln.init(k_ln2, in_shape)[0],
            "mlp_w1": init_weight(k_m1, (d, self.mlp_dim)),
            "mlp_b1": jnp.zeros((self.mlp_dim,), jnp.float32),
            "mlp_w2": init_weight(k_m2, (self.mlp_dim, d)),
            "mlp_b2": jnp.zeros((d,), jnp.float32),
        }
        return params, tuple(in_shape)

    takes_segment_ids = True

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None, segment_ids=None):
        ln = LayerNormalization()
        drop_rngs = (jax.random.split(rng, 2) if rng is not None else
                     (None, None))

        h = ln.apply(params["ln1"], x, compute_dtype=compute_dtype)
        h = self._mha().apply(params["attn"], h, compute_dtype=compute_dtype,
                              train=train, rng=None,
                              segment_ids=segment_ids)
        x = x + _dropout(drop_rngs[0], self.dropout, h.astype(x.dtype), train)

        h = ln.apply(params["ln2"], x, compute_dtype=compute_dtype)
        h = _project(h, params["mlp_w1"], params["mlp_b1"], compute_dtype)
        h = _apply_activation(self.activation, h).astype(compute_dtype)
        h = _project(h, params["mlp_w2"], params["mlp_b2"], compute_dtype)
        return x + _dropout(drop_rngs[1], self.dropout, h.astype(x.dtype),
                            train)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int):
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)

    def init(self, rng, in_shape):
        params = {"embedding": 0.02 * jax.random.normal(
            rng, (self.input_dim, self.output_dim), jnp.float32)}
        return params, tuple(in_shape) + (self.output_dim,)

    def apply(self, params, x, *, compute_dtype=jnp.bfloat16, train=False,
              rng=None):
        return params["embedding"].astype(compute_dtype)[x]
