"""Loss functions (Keras-name parity).

The reference passes Keras loss *names* into trainers (reference:
``distkeras/trainers.py :: Trainer.__init__(..., loss)`` compiled in
``workers.py :: SequentialWorker.prepare_model``).  We accept the same string
names and resolve them to pure jnp functions.  All losses reduce to a scalar
mean over the batch and compute in float32 regardless of model compute dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def categorical_crossentropy(y_true, y_pred, from_logits: bool = False):
    y_true = y_true.astype(jnp.float32)
    y_pred = y_pred.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(y_pred, axis=-1)
    else:
        logp = jnp.log(jnp.clip(y_pred, _EPS, 1.0))
    return -jnp.mean(jnp.sum(y_true * logp, axis=-1))


def sparse_categorical_crossentropy(y_true, y_pred, from_logits: bool = False):
    y_pred = y_pred.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(y_pred, axis=-1)
    else:
        logp = jnp.log(jnp.clip(y_pred, _EPS, 1.0))
    idx = y_true.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, idx[..., None], axis=-1)[..., 0]
    return -jnp.mean(picked)


def masked_sparse_categorical_crossentropy(y_true, y_pred,
                                           from_logits: bool = False):
    """Sparse CE that skips label < 0 (the sequence-packing convention:
    ``data/packing.py :: packed_lm_labels`` marks cross-document and
    padding positions -1).  Mean over the VALID positions only."""
    y_pred = y_pred.astype(jnp.float32)
    if from_logits:
        logp = jax.nn.log_softmax(y_pred, axis=-1)
    else:
        logp = jnp.log(jnp.clip(y_pred, _EPS, 1.0))
    idx = y_true.astype(jnp.int32)
    valid = idx >= 0
    picked = jnp.take_along_axis(
        logp, jnp.maximum(idx, 0)[..., None], axis=-1)[..., 0]
    count = jnp.maximum(jnp.sum(valid), 1)
    return -jnp.sum(jnp.where(valid, picked, 0.0)) / count


def binary_crossentropy(y_true, y_pred, from_logits: bool = False):
    y_true = y_true.astype(jnp.float32)
    y_pred = y_pred.astype(jnp.float32)
    if from_logits:
        # numerically stable sigmoid BCE
        return jnp.mean(jnp.maximum(y_pred, 0) - y_pred * y_true +
                        jnp.log1p(jnp.exp(-jnp.abs(y_pred))))
    p = jnp.clip(y_pred, _EPS, 1.0 - _EPS)
    return -jnp.mean(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))


def mean_squared_error(y_true, y_pred):
    d = y_true.astype(jnp.float32) - y_pred.astype(jnp.float32)
    return jnp.mean(jnp.square(d))


def mean_absolute_error(y_true, y_pred):
    return jnp.mean(jnp.abs(
        y_true.astype(jnp.float32) - y_pred.astype(jnp.float32)))


def mean_absolute_percentage_error(y_true, y_pred):
    y_true = y_true.astype(jnp.float32)
    diff = jnp.abs((y_true - y_pred.astype(jnp.float32))
                   / jnp.clip(jnp.abs(y_true), _EPS, None))
    return 100.0 * jnp.mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    fl = jnp.log1p(jnp.clip(y_pred.astype(jnp.float32), _EPS, None))
    sl = jnp.log1p(jnp.clip(y_true.astype(jnp.float32), _EPS, None))
    return jnp.mean(jnp.square(fl - sl))


def kullback_leibler_divergence(y_true, y_pred):
    y_true = jnp.clip(y_true.astype(jnp.float32), _EPS, 1.0)
    y_pred = jnp.clip(y_pred.astype(jnp.float32), _EPS, 1.0)
    return jnp.mean(jnp.sum(y_true * jnp.log(y_true / y_pred), axis=-1))


def hinge(y_true, y_pred):
    """Hinge loss with {0,1} labels auto-converted to {-1,1}.

    DELIBERATE MODERNIZATION vs Keras-1: upstream Keras-1 performed no label
    conversion (that arrived in Keras 2), so a reference workflow feeding
    0/1 labels under this name effectively trained on a different objective
    (the 0-label rows contribute a constant margin).  We adopt the Keras-2+
    conversion because 0/1 one-hot labels are what this framework's own
    pipeline produces; documented here and in docs/API.md.
    """
    y_true = y_true.astype(jnp.float32)
    y_true = jnp.where(y_true == 0.0, -1.0, y_true)
    return jnp.mean(jnp.maximum(
        1.0 - y_true * y_pred.astype(jnp.float32), 0.0))


def squared_hinge(y_true, y_pred):
    # same deliberate {0,1}->{-1,1} modernization as ``hinge`` above
    y_true = y_true.astype(jnp.float32)
    y_true = jnp.where(y_true == 0.0, -1.0, y_true)
    return jnp.mean(jnp.square(jnp.maximum(
        1.0 - y_true * y_pred.astype(jnp.float32), 0.0)))


def poisson(y_true, y_pred):
    y_pred = jnp.clip(y_pred.astype(jnp.float32), _EPS, None)
    return jnp.mean(y_pred - y_true.astype(jnp.float32) * jnp.log(y_pred))


def cosine_proximity(y_true, y_pred):
    """Keras-1 cosine proximity, reduction included.

    Keras-1 computed ``-mean(l2_normalize(y_true) * l2_normalize(y_pred))``
    — the mean runs over ALL elements, not per-row, so a perfectly aligned
    pair scores ``-1/feature_dim`` (NOT -1).  We reproduce that exactly so
    migrated configs using this loss name keep the same values and gradient
    scale as the reference (a per-row mean would be feature_dim x larger).
    Minimizing still drives vectors together.
    """
    yt = y_true.astype(jnp.float32)
    yp = y_pred.astype(jnp.float32)
    yt = yt / jnp.clip(jnp.linalg.norm(yt, axis=-1, keepdims=True), _EPS)
    yp = yp / jnp.clip(jnp.linalg.norm(yp, axis=-1, keepdims=True), _EPS)
    return -jnp.mean(yt * yp)


def _from_logits(fn):
    def wrapped(y_true, y_pred):
        return fn(y_true, y_pred, from_logits=True)
    return wrapped


_LOSSES = {
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "categorical_crossentropy_from_logits":
        _from_logits(categorical_crossentropy),
    "sparse_categorical_crossentropy_from_logits":
        _from_logits(sparse_categorical_crossentropy),
    "sparse_categorical_crossentropy_masked":
        masked_sparse_categorical_crossentropy,
    "sparse_categorical_crossentropy_masked_from_logits":
        _from_logits(masked_sparse_categorical_crossentropy),
    "binary_crossentropy_from_logits": _from_logits(binary_crossentropy),
    "mean_squared_error": mean_squared_error,
    "mse": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
    "mae": mean_absolute_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "mape": mean_absolute_percentage_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "msle": mean_squared_logarithmic_error,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "kld": kullback_leibler_divergence,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "cosine": cosine_proximity,
}


def get_loss(name):
    """Resolve a Keras-style loss name (or pass through a callable)."""
    if callable(name):
        return name
    try:
        return _LOSSES[name]
    except KeyError:
        raise ValueError(
            f"Unknown loss {name!r}; known: {sorted(_LOSSES)}") from None


def per_example(loss_fn):
    """Lift any mean-reducing loss to per-example form: vmap it over
    singleton batches, giving a (batch,) vector of losses.  Works for custom
    callables too, so the padding/masking path (``shape_epoch_data`` pads the
    tail round; padded rows get weight 0) needs no per-loss rewrites."""
    def fn(y_true, y_pred):
        return jax.vmap(lambda yt, yp: loss_fn(yt[None], yp[None]))(
            y_true, y_pred)
    return fn
