"""Sequential model: a list of layer specs + a params pytree.

Replaces the reference's Keras-model handling (reference:
``distkeras/utils.py :: serialize_keras_model / deserialize_keras_model``,
which pickle ``model.to_json()`` + ``model.get_weights()``).  Here the model
*spec* is JSON-able layer configs and the *weights* are a pytree, so the whole
forward/backward is a pure jittable function — the shape XLA wants.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .layers import Layer

Params = Any


class Sequential:
    """A stack of layer specs with a functional (init/apply) interface.

    Unlike Keras, the model object holds no weights: ``init`` returns the
    params pytree and ``apply`` consumes it.  ``compute_dtype`` defaults to
    bfloat16 — matmuls/convs run on the MXU in bf16 with f32 accumulation.
    """

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 input_shape: Optional[Sequence[int]] = None,
                 compute_dtype: str = "bfloat16", name: str = "sequential"):
        self.layers: List[Layer] = list(layers) if layers else []
        self.input_shape = tuple(input_shape) if input_shape else None
        self.compute_dtype = compute_dtype
        self.name = name

    # -- construction -------------------------------------------------------
    def add(self, layer: Layer) -> "Sequential":
        self.layers.append(layer)
        return self

    @property
    def _cdtype(self):
        return jnp.dtype(self.compute_dtype)

    # -- functional core ----------------------------------------------------
    def init(self, rng, input_shape: Optional[Sequence[int]] = None) -> Params:
        """Initialize params. ``input_shape`` excludes the batch dim."""
        shape = tuple(input_shape) if input_shape else self.input_shape
        if shape is None:
            raise ValueError("input_shape required (constructor or init())")
        self.input_shape = shape
        params = []
        for layer in self.layers:
            rng, sub = jax.random.split(rng)
            p, shape = layer.init(sub, shape)
            params.append(p)
        self.output_shape = shape
        return params

    def apply(self, params: Params, x, *, train: bool = False, rng=None,
              stats_out: Optional[dict] = None, segment_ids=None):
        """Pure forward pass. Safe to jit / grad / vmap / shard_map.

        ``stats_out``: optional dict filled (at trace time) with
        ``{layer_index: new_stats}`` for stat-carrying layers (BatchNorm) when
        ``train=True`` — the train step merges these back into params via
        ``merge_stats`` after the optimizer update.

        ``segment_ids`` (B, S): sequence-packing isolation — forwarded to
        every attention-bearing layer (``takes_segment_ids``) so packed
        documents attend only within themselves (``data/packing.py``).
        Requires relative positions: an absolute additive table
        (``PositionalEmbedding``) would hand a mid-row document shifted
        position vectors — silently different training than unpacked —
        so that combination is refused.
        """
        if segment_ids is not None:
            from .layers import PositionalEmbedding
            if any(isinstance(l, PositionalEmbedding) for l in self.layers):
                raise ValueError(
                    "sequence packing (segment_ids) requires relative "
                    "positions: this model has an absolute "
                    "PositionalEmbedding table, which would give packed "
                    "documents position-shifted embeddings — build the "
                    "model with positional='rope'")
        cdtype = self._cdtype
        for i, layer in enumerate(self.layers):
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            kw = ({"segment_ids": segment_ids}
                  if segment_ids is not None
                  and getattr(layer, "takes_segment_ids", False) else {})
            if (train and stats_out is not None
                    and hasattr(layer, "apply_with_stats")):
                x, new_stats = layer.apply_with_stats(
                    params[i], x, compute_dtype=cdtype, rng=sub)
                stats_out[i] = new_stats
            else:
                x = layer.apply(params[i], x, compute_dtype=cdtype,
                                train=train, rng=sub, **kw)
        return x

    @staticmethod
    def merge_stats(params: Params, stats: dict) -> Params:
        """Write ``{layer_index: new_stats}`` (from ``apply(stats_out=...)``)
        into a params pytree, leaving trained leaves untouched."""
        if not stats:
            return params
        out = list(params)
        for i, s in stats.items():
            out[i] = {**out[i], "stats": s}
        return out

    def has_stats(self) -> bool:
        return any(hasattr(layer, "apply_with_stats")
                   for layer in self.layers)

    def __call__(self, params, x, **kw):
        return self.apply(params, x, **kw)

    # -- keras-parity conveniences ------------------------------------------
    def predict(self, params, x, batch_size: int = 512):
        """Batched host-side inference (used by predictors.ModelPredictor)."""
        fn = jax.jit(lambda p, b: self.apply(p, b, train=False))
        outs = []
        x = np.asarray(x)
        for i in range(0, len(x), batch_size):
            outs.append(np.asarray(fn(params, x[i:i + batch_size])))
        return np.concatenate(outs, axis=0)

    def count_params(self, params) -> int:
        from .quant import QuantizedTensor
        # QuantizedTensor is one logical weight: count its .shape, not its
        # (codes + scale) component leaves
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(
                       params,
                       is_leaf=lambda x: isinstance(x, QuantizedTensor)))

    # -- (de)serialization ---------------------------------------------------
    def to_json(self) -> str:
        return json.dumps({
            "name": self.name,
            "compute_dtype": self.compute_dtype,
            "input_shape": list(self.input_shape) if self.input_shape else None,
            "layers": [layer.get_config() for layer in self.layers],
        })

    @staticmethod
    def from_json(spec: str) -> "Sequential":
        cfg = json.loads(spec)
        model = Sequential(
            [Layer.from_config(c) for c in cfg["layers"]],
            input_shape=cfg.get("input_shape"),
            compute_dtype=cfg.get("compute_dtype", "bfloat16"),
            name=cfg.get("name", "sequential"),
        )
        return model

    def get_weights(self, params) -> List[np.ndarray]:
        """Flat list of np arrays in deterministic (pytree) order —
        the wire/storage format, mirroring Keras ``model.get_weights()``."""
        return [np.asarray(w) for w in jax.tree_util.tree_leaves(params)]

    def set_weights(self, params: Params, weights: Sequence[np.ndarray]):
        leaves, treedef = jax.tree_util.tree_flatten(params)
        if len(leaves) != len(weights):
            raise ValueError(
                f"weight count mismatch: {len(leaves)} vs {len(weights)}")
        new = [jnp.asarray(w, dtype=l.dtype) for l, w in zip(leaves, weights)]
        return jax.tree_util.tree_unflatten(treedef, new)


class FittedModel:
    """A (spec, params) pair — what ``Trainer.train`` returns.

    Plays the role of the trained ``keras.Model`` the reference hands back
    (reference: ``trainers.py :: DistributedTrainer.train`` returns the PS
    center model).  Carries enough surface (predict / get_weights / save) for
    the predictor+evaluator pipeline.
    """

    def __init__(self, model: Sequential, params: Params):
        self.model = model
        self.params = params

    def predict(self, x, batch_size: int = 512):
        return self.model.predict(self.params, x, batch_size=batch_size)

    def get_weights(self):
        return self.model.get_weights(self.params)

    def set_weights(self, weights):
        self.params = self.model.set_weights(self.params, weights)
        return self

    def count_params(self):
        return self.model.count_params(self.params)

    def quantize(self) -> "FittedModel":
        """Weight-only int8 post-training quantization for serving: matmul
        kernels become (int8, per-channel scale) leaves that dequantize
        inside the existing forward/decode code (``core.quant``); predict
        and generate work unchanged at ~half the bf16 weight traffic."""
        from .quant import quantize_params
        return FittedModel(self.model, quantize_params(self.params))

    def generate(self, prompt, num_steps: int, temperature: float = 0.0,
                 rng=None, max_len=None, rolling: bool = False, **kw):
        """KV-cache autoregressive continuation (causal LMs only) — see
        ``core.decode.generate`` (``**kw`` passes through its sampling/
        stopping surface: ``top_k``, ``top_p``, ``eos_id``, ``pad_id``)."""
        from .decode import generate
        return generate(self.model, self.params, prompt, num_steps,
                        temperature=temperature, rng=rng, max_len=max_len,
                        rolling=rolling, **kw)

    def speculative_generate(self, draft: "FittedModel", prompt,
                             num_steps: int, draft_len: int = 4, **kw):
        """Decoding accelerated by a cheaper ``draft`` model — greedy by
        default; with ``temperature``/``top_k``/``top_p``/``rng`` it is
        distribution-exact speculative SAMPLING (see
        ``core.decode.speculative_generate``; ``**kw`` also takes
        ``max_len``, ``return_stats``)."""
        from .decode import speculative_generate
        return speculative_generate(self.model, self.params, draft.model,
                                    draft.params, prompt, num_steps,
                                    draft_len=draft_len, **kw)

    def beam_search(self, prompt, num_steps: int, num_beams: int = 4, **kw):
        """Deterministic top-``num_beams`` continuation search (causal LMs)
        — see ``core.decode.beam_search`` (``**kw``: ``length_penalty``,
        ``eos_id``, ``pad_id``).  Returns (tokens (B, beams, P+steps),
        scores), best beam first."""
        from .decode import beam_search
        return beam_search(self.model, self.params, prompt, num_steps,
                           num_beams=num_beams, **kw)

    def serialize(self) -> dict:
        return serialize_model(self.model, self.params)

    @staticmethod
    def deserialize(blob: dict) -> "FittedModel":
        model, params = deserialize_model(blob)
        return FittedModel(model, params)

    def save(self, path: str):
        """Persist spec+weights as .npz (final-model persistence; the
        reference's only persistence was ``model.save`` on the returned
        Keras model)."""
        write_npz_blob(path, self.serialize())

    @staticmethod
    def load(path: str) -> "FittedModel":
        return FittedModel.deserialize(read_npz_blob(path))


def write_npz_blob(path: str, blob: dict) -> None:
    """The framework's ONE npz model layout (``spec`` json bytes + ``w{i}``
    weight arrays) — shared by ``FittedModel.save`` and the process-worker
    shipping path, which writes straight from a blob without re-tracing."""
    weights = {f"w{i}": np.asarray(w) for i, w in enumerate(blob["weights"])}
    np.savez(path, spec=np.frombuffer(blob["model"].encode(),
                                      dtype=np.uint8), **weights)


def read_npz_blob(path: str) -> dict:
    with np.load(path) as z:
        spec = bytes(z["spec"]).decode()
        weights = [z[f"w{i}"] for i in range(len(z.files) - 1)]
    return {"model": spec, "weights": weights}


def serialize_model(model: Sequential, params: Params) -> dict:
    """Parity with reference ``serialize_keras_model`` (utils.py):
    returns a picklable dict {'model': json_spec, 'weights': [ndarray...]}."""
    from .quant import QuantizedTensor
    if any(isinstance(l, QuantizedTensor) for l in jax.tree_util.tree_leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor))):
        raise ValueError(
            "cannot serialize int8-quantized params (the npz/wire layout is "
            "a flat full-precision weight list): save the unquantized model "
            "and call .quantize() after load")
    return {"model": model.to_json(), "weights": model.get_weights(params)}


def deserialize_model(blob: dict) -> Tuple[Sequential, Params]:
    """Parity with reference ``deserialize_keras_model`` (utils.py)."""
    model = Sequential.from_json(blob["model"])
    if model.input_shape is None:
        raise ValueError("serialized model missing input_shape")
    params = model.init(jax.random.PRNGKey(0), model.input_shape)
    params = model.set_weights(params, blob["weights"])
    return model, params
