from .layers import (Layer, Dense, Conv2D, MaxPooling2D, AveragePooling2D,
                     GlobalAveragePooling2D, Flatten, Reshape, Activation,
                     Dropout, BatchNormalization, Embedding, get_activation,
                     LayerNormalization, PositionalEmbedding,
                     MultiHeadAttention, TransformerBlock)
from .model import Sequential, serialize_model, deserialize_model
from .decode import decode_step, generate, init_cache, jit_decode_step
from .losses import get_loss
from .optimizers import (Optimizer, SGD, Adam, Adagrad, Adadelta, RMSprop,
                         get_optimizer)
from .train import TrainState, make_train_step, make_epoch_runner, init_state

__all__ = [
    "Layer", "Dense", "Conv2D", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "Flatten", "Reshape", "Activation", "Dropout",
    "BatchNormalization", "Embedding", "get_activation",
    "LayerNormalization", "PositionalEmbedding", "MultiHeadAttention",
    "TransformerBlock",
    "Sequential", "serialize_model", "deserialize_model",
    "decode_step", "generate", "init_cache", "jit_decode_step",
    "get_loss",
    "Optimizer", "SGD", "Adam", "Adagrad", "Adadelta", "RMSprop",
    "get_optimizer",
    "TrainState", "make_train_step", "make_epoch_runner", "init_state",
]
