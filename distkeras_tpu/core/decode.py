"""Autoregressive decoding with a KV cache for Sequential causal LMs.

No reference counterpart (SURVEY.md §2.3: the reference has no sequence
models at all) — this completes the long-context layer's inference story.
Training materializes attention over the full sequence; decoding re-runs
one token at a time against cached k/v, so each step is O(S) instead of
O(S²), and with grouped-query attention (``MultiHeadAttention
num_kv_heads``) the cache shrinks by ``num_heads / num_kv_heads``.

Design: rather than adding an incremental-apply method to every layer, one
walker here understands the sequence-model layer kinds (``Embedding``,
``PositionalEmbedding``, ``TransformerBlock``, ``LayerNormalization``,
``Dense``) and reuses their own helpers (``_project``,
``LayerNormalization.apply``) plus ``ops.attention.dot_product_attention``
(via its ``q_offset``/``kv_length`` hooks), so decode numerics ARE the
full-forward numerics — there is no forked attention implementation.

The walker is length-generic: ``generate`` prefills the whole prompt in
ONE batched forward (MXU-shaped (B, P, D) matmuls, all P cache slots
written in parallel), then scans single-token steps for the continuation.
``decode_step`` is the jittable single-token form.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

tmap = jax.tree_util.tree_map

from .layers import (Dense, Embedding, LayerNormalization,
                     MultiHeadAttention, PositionalEmbedding,
                     TransformerBlock, _apply_activation, _project)

_STATELESS = (LayerNormalization, Dense)


def _check_supported(model) -> None:
    for layer in model.layers:
        if not isinstance(layer, (Embedding, PositionalEmbedding,
                                  TransformerBlock) + _STATELESS):
            raise ValueError(
                f"decode: unsupported layer kind {layer.kind!r} — KV-cache "
                "decoding walks Embedding/PositionalEmbedding/"
                "TransformerBlock/LayerNormalization/Dense sequences "
                "(the transformer_lm family)")
        if isinstance(layer, TransformerBlock) and not layer.causal:
            raise ValueError(
                "decode: TransformerBlock(causal=False) — autoregressive "
                "decoding is only meaningful for causal models, and the "
                "cached step would silently diverge from the full forward")


def _context_limit(model) -> Optional[int]:
    for layer in model.layers:
        if isinstance(layer, PositionalEmbedding):
            return layer.max_len
    return None


def _vocab_size(model) -> Optional[int]:
    for layer in model.layers:
        if isinstance(layer, Embedding):
            return layer.input_dim
    return None


def _validate_rolling(model) -> None:
    """Every block must carry a window for a ring cache to be sound:
    without one, old positions stay visible and must stay cached."""
    for layer in model.layers:
        if isinstance(layer, TransformerBlock) and \
                layer._mha().attention_window is None:
            raise ValueError(
                "rolling=True needs attention_window on every "
                "TransformerBlock: without a window, old positions stay "
                "visible and must stay cached")


def init_cache(model, batch: int, max_len: int,
               rolling: bool = False, kv_dtype: Optional[str] = None,
               ring_slack: int = 0) -> List[Any]:
    """One cache slot per layer: ``{"k", "v"}`` of shape
    (batch, max_len, num_kv_heads, key_dim) for TransformerBlocks, None
    elsewhere.  Cache dtype = the model's compute dtype (bf16 on TPU).

    ``rolling=True`` (sliding-window models only): each block's cache is a
    ring buffer of its ``attention_window`` slots instead of ``max_len`` —
    slot ``p % W`` holds position ``p``, old entries are overwritten as
    generation advances, and memory stays O(W) however long the
    continuation runs (the point of windowed attention at decode time).
    ``ring_slack`` widens each ring by that many EXTRA slots (modulus
    W + slack): entries survive ``slack`` positions past the window, which
    is what makes multi-token per-row steps (the serving engine's
    speculative verify, L = spec_len + 1) exact on rolling pools — a
    query at the oldest position in the write window still finds its
    full attention window un-overwritten.

    ``kv_dtype="int8"``: entries are stored as int8 codes plus a
    per-(row, slot, head) f32 scale (``{"k", "v", "ks", "vs"}``),
    quantized at write time and dequantized inside the attention read
    (``core.quant.quantize_kv``) — roughly half the slot bytes of a bf16
    pool, 4× down from f32.  Written through the per-row (serving)
    decode paths only; offline scalar-position walkers keep their
    full-precision caches."""
    _check_supported(model)
    if rolling:
        _validate_rolling(model)
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype must be None or 'int8', got "
                         f"{kv_dtype!r}")
    limit = _context_limit(model)
    if limit is not None and max_len > limit:
        raise ValueError(
            f"cache max_len {max_len} exceeds the model's positional-"
            f"embedding range {limit} — positions past it have no trained "
            "embedding (the full forward rejects such sequences too)")
    dtype = model._cdtype
    caches: List[Any] = []
    for layer in model.layers:
        if isinstance(layer, TransformerBlock):
            mha = layer._mha()
            slots = max_len
            if rolling:
                slots = min(mha.attention_window + int(ring_slack), max_len)
            shape = (batch, slots, mha._kv_heads(), mha.key_dim)
            if kv_dtype == "int8":
                caches.append({"k": jnp.zeros(shape, jnp.int8),
                               "v": jnp.zeros(shape, jnp.int8),
                               "ks": jnp.zeros(shape[:3], jnp.float32),
                               "vs": jnp.zeros(shape[:3], jnp.float32)})
            else:
                caches.append({"k": jnp.zeros(shape, dtype),
                               "v": jnp.zeros(shape, dtype)})
        else:
            caches.append(None)
    return caches


class PagedView:
    """Static+traced description of a paged-KV access, threaded through
    the decode walker (``_forward(paged=...)``): ``tables`` (B, T) int32
    per-row block tables (traced), ``page`` tokens per block and ``view``
    the logical sequence length (both STATIC — construct this object
    INSIDE the jitted program, closing over the ints).  ``floor``/``ceil``
    (B,) bound each row's write range: logical positions below ``floor``
    (a shared — refcounted — prefix another request owns) or at/above
    ``ceil`` (right-pad junk past the real prompt) are routed into the
    arena's null block instead of written.  ``qcap`` (B,) clamps pad
    QUERY positions onto the last real position (see
    ``ops.attention.dot_product_attention(q_positions=)``).  ``ring``
    lays logical positions out modulo ``view`` (the paged form of the
    rolling ring — same slot-holds-``p % view`` contract, addressed
    through the block table)."""

    __slots__ = ("tables", "page", "view", "floor", "ceil", "qcap", "ring")

    def __init__(self, tables, page: int, view: int, floor=None, ceil=None,
                 qcap=None, ring: bool = False):
        self.tables = tables
        self.page = int(page)
        self.view = int(view)
        self.floor = floor
        self.ceil = ceil
        self.qcap = qcap
        self.ring = bool(ring)


def init_paged_arena(model, num_blocks: int, block_size: int,
                     kv_dtype: Optional[str] = None) -> List[Any]:
    """The paged slot pool's backing store: per TransformerBlock a FLAT
    arena of ``num_blocks + 1`` fixed-size blocks laid out contiguously —
    ``{"k", "v"}`` of shape ((num_blocks + 1) * block_size, num_kv_heads,
    key_dim) (plus ``{"ks", "vs"}`` per-entry scales for
    ``kv_dtype="int8"``, quantized codes paged identically to the
    full-precision entries).  Physical block b owns arena slots
    [b * block_size, (b + 1) * block_size); logical position p of a
    request whose block table maps logical block ``p // block_size`` to b
    lives at slot ``b * block_size + p % block_size``.  The EXTRA
    trailing block (id ``num_blocks``) is the NULL block: free slots'
    junk decode writes, right-pad prefill writes, and warmup all route
    there, so no real request's blocks are ever touched by another row's
    program.  Unlike ``init_cache`` there is no per-slot ``max_len``
    axis — capacity is ``num_blocks × block_size`` TOKENS, allocated on
    demand per request instead of ``num_slots × max_len`` up front."""
    _check_supported(model)
    if kv_dtype not in (None, "int8"):
        raise ValueError(f"kv_dtype must be None or 'int8', got "
                         f"{kv_dtype!r}")
    if int(num_blocks) < 1:
        raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
    if int(block_size) < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    arena_len = (int(num_blocks) + 1) * int(block_size)
    dtype = model._cdtype
    caches: List[Any] = []
    for layer in model.layers:
        if isinstance(layer, TransformerBlock):
            mha = layer._mha()
            shape = (arena_len, mha._kv_heads(), mha.key_dim)
            if kv_dtype == "int8":
                caches.append({"k": jnp.zeros(shape, jnp.int8),
                               "v": jnp.zeros(shape, jnp.int8),
                               "ks": jnp.zeros(shape[:2], jnp.float32),
                               "vs": jnp.zeros(shape[:2], jnp.float32)})
            else:
                caches.append({"k": jnp.zeros(shape, dtype),
                               "v": jnp.zeros(shape, dtype)})
        else:
            caches.append(None)
    return caches


def _kv_quantized(cache) -> bool:
    """True for an int8 KV cache dict (codes + per-entry scales)."""
    return isinstance(cache, dict) and "ks" in cache


def _kv_write(cache, idx, k_t, v_t):
    """Scatter a (B, L, Hkv, Dh) k/v write into ``cache`` at ``idx`` (a
    tuple of broadcastable row/slot index arrays); int8 caches quantize on
    write, storing codes and per-entry scales side by side.  Out-of-bounds
    indices drop (jit scatter semantics) — the serving engine's
    speculative verify leans on that at the end-of-request boundary."""
    if _kv_quantized(cache):
        from .quant import quantize_kv
        kq, ks = quantize_kv(k_t)
        vq, vs = quantize_kv(v_t)
        return {"k": cache["k"].at[idx].set(kq),
                "v": cache["v"].at[idx].set(vq),
                "ks": cache["ks"].at[idx].set(ks),
                "vs": cache["vs"].at[idx].set(vs)}
    return {"k": cache["k"].at[idx].set(k_t),
            "v": cache["v"].at[idx].set(v_t)}


def _kv_read(cache, dtype):
    """The attention-side view of a cache: dense (codes × scales for int8
    caches — fused into the consuming matmuls under jit)."""
    if _kv_quantized(cache):
        from .quant import dequantize_kv
        return (dequantize_kv(cache["k"], cache["ks"], dtype),
                dequantize_kv(cache["v"], cache["vs"], dtype))
    return cache["k"], cache["v"]


def gather_blocks(caches, rows):
    """Pull the arena slots named by ``rows`` (a flat (n,) int32 vector of
    PHYSICAL slot indices — block table rows expanded by ``block_size``)
    out of a flat paged arena (``init_paged_arena``): per TransformerBlock
    a dict of ``(n, Hkv, Dh)`` payloads (int8 arenas also gather their
    ``(n, Hkv)`` scales).  The prefill half of a disaggregated transfer —
    read-only, so gathering a radix-shared prefix block is safe.  Shape is
    static in ``rows.shape``: callers pad ``rows`` with null-block slots
    to a fixed length to keep one trace."""
    return [None if c is None else
            {k: jnp.take(v, rows, axis=0) for k, v in c.items()}
            for c in caches]


def scatter_blocks(caches, rows, payload):
    """The decode half: write ``payload`` (the ``gather_blocks`` layout)
    into this arena's slots ``rows`` — the receiver's OWN physical slots
    for the shipped logical blocks.  Junk rows in a fixed-shape transfer
    are padded to the null block on the caller's side, where the write is
    harmless by the arena contract."""
    return [c if c is None else
            {k: v.at[rows].set(payload[i][k]) for k, v in c.items()}
            for i, c in enumerate(caches)]


def gather_slot_state(caches, rows, tok, pos, keys, slot):
    """The suspend half of a QoS preemption swap-out: one jitted dispatch
    returning the arena slots named by ``rows`` (``gather_blocks``
    layout) TOGETHER with the preempted slot's device-resident decode
    frontier — its current un-written token (``tok[slot]``), position
    (``pos[slot]``, entries written so far), and RNG key row.  The
    frontier must come off the device in the same dispatch as the blocks:
    the pair (KV prefix, frontier) is what makes a later re-install
    bit-identical, and reading the device copy (not a host mirror) makes
    the snapshot authoritative by construction."""
    payload = gather_blocks(caches, rows)
    return payload, tok[slot], pos[slot], keys[slot]


def _per_row(pos) -> bool:
    """True when ``pos`` is a (B,) per-row position vector (the serving
    engine's slot pool) rather than the scalar all-rows-share-one-position
    form.  Scalar ``pos`` keeps the exact original code path."""
    return getattr(pos, "ndim", 0) == 1


def _mha_forward(mha: MultiHeadAttention, params, h, cache, pos, cdtype,
                 rolling: bool = False, paged: Optional[PagedView] = None):
    """Cached attention over (B, L, D) queries starting at position
    ``pos``; writes k/v for those L positions into the cache and attends
    through ``ops.attention.dot_product_attention`` (same numerics as the
    training forward).  ``pos`` may be a (B,) vector: each row writes its
    k/v at — and attends from — its own position, and per-row positions
    compose with L > 1 (the serving engine's speculative verify: L =
    spec_len + 1 entries written at each row's own offsets, all L queries
    scored in this one forward).  Rolling caches additionally need a ring
    of >= window + L - 1 slots for L > 1 (``init_cache(ring_slack=...)``)
    so the oldest query's attention window survives the newest write.

    Right-padded batches (the serving engine's bucketed prefill pads a
    mixed-length prompt batch to one bucket length) need no extra
    masking here: pad tokens sit at positions >= every real query, so the
    causal mask already keeps their keys out of every real row's softmax,
    and their (finite) junk cache entries stay behind each row's decode
    ``kv_length`` frontier until real writes overwrite them.  (An explicit
    per-row kv_length mask would be WRONG for windowed models: a pad
    query whose window has slid past the real prompt would mask every
    key, and the resulting empty-softmax NaN row poisons real outputs
    through the next layer's ``0 * NaN`` value products.)

    ``paged`` (a :class:`PagedView`): the cache is a FLAT block arena
    (``init_paged_arena``) addressed through per-row block tables instead
    of a (B, S, ...) slab.  Writes scatter at gather-computed physical
    slots (``floor``/``ceil`` route shared-prefix and right-pad positions
    into the null block); reads gather each row's logical view back out
    (``ops.attention.paged_gather``) and attend with the SAME per-row
    masks as the dense path — the paged step is a storage relayout, not
    a numerics change.  Requires per-row ``pos``."""
    from ..ops.attention import dot_product_attention, paged_gather
    b, length = h.shape[0], h.shape[1]
    dh = mha.key_dim
    per_row = _per_row(pos)
    q_clamped = None
    if paged is not None:
        if not per_row:
            raise ValueError("paged KV access needs per-row (B,) positions")
        q_idx = pos[:, None] + jnp.arange(length)[None, :]       # (B, L)
        q_clamped = (q_idx if paged.qcap is None
                     else jnp.minimum(q_idx, paged.qcap[:, None]))

    def proj(name, heads):
        bias = params.get("b" + name[1]) if mha.use_bias else None
        y = _project(h, params[name], bias, cdtype)
        return y.astype(cdtype).reshape(b, length, heads, dh)

    q = proj("wq", mha.num_heads)
    k_t = proj("wk", mha._kv_heads())
    v_t = proj("wv", mha._kv_heads())
    if mha.rope:
        # rotate by the suffix's ABSOLUTE positions; cached k stay rotated
        # by their own positions (RoPE scores depend only on distance)
        from ..ops.rope import apply_rope
        if q_clamped is not None:
            positions = q_clamped
        else:
            positions = (pos[:, None] + jnp.arange(length)[None, :]
                         if per_row else pos + jnp.arange(length))
        q = apply_rope(q, positions, mha.rope_theta, mha.rope_scale)
        k_t = apply_rope(k_t, positions, mha.rope_theta, mha.rope_scale)
    new_cache = None
    if paged is not None:
        # -- paged arena: block-table-indexed scatter write, gathered read
        bs, view = paged.page, paged.view
        idx = pos[:, None] + jnp.arange(length)[None, :]         # (B, L)
        if paged.ring:
            w = view
            if length > 1 and w < mha.attention_window + length - 1:
                raise ValueError(
                    f"multi-token per-row steps on a paged ring need a "
                    f"view of >= window + L - 1 = "
                    f"{mha.attention_window + length - 1} slots, got {w} "
                    f"— the oldest query's window would be overwritten "
                    f"by the newest write")
            lidx = idx % w
        else:
            lidx = idx
        blk = jnp.minimum(lidx // bs, paged.tables.shape[1] - 1)
        phys = (jnp.take_along_axis(paged.tables, blk, axis=1) * bs
                + lidx % bs)
        null_phys = cache["k"].shape[0] - 1  # inside the null block
        if paged.floor is not None:
            phys = jnp.where(idx >= jnp.reshape(paged.floor, (-1, 1)),
                             phys, null_phys)
        if paged.ceil is not None:
            phys = jnp.where(idx < jnp.reshape(paged.ceil, (-1, 1)),
                             phys, null_phys)
        new_cache = _kv_write(cache, (phys,), k_t, v_t)
        if _kv_quantized(new_cache):
            from .quant import dequantize_kv
            k = dequantize_kv(
                paged_gather(new_cache["k"], paged.tables, bs, view),
                paged_gather(new_cache["ks"], paged.tables, bs, view),
                cdtype)
            v = dequantize_kv(
                paged_gather(new_cache["v"], paged.tables, bs, view),
                paged_gather(new_cache["vs"], paged.tables, bs, view),
                cdtype)
        else:
            k = paged_gather(new_cache["k"], paged.tables, bs, view)
            v = paged_gather(new_cache["v"], paged.tables, bs, view)
        if paged.ring:
            # same frontier layout as the dense ring: view slot j holds
            # the newest position <= each row's write frontier congruent
            # to j mod view (negative = never written)
            front = pos[:, None] + (length - 1)
            j = jnp.arange(view)
            kv_positions = front - jnp.mod(front - j[None, :], view)
            out = dot_product_attention(q, k, v, causal=True,
                                        q_positions=q_clamped,
                                        window=mha.attention_window,
                                        kv_positions=kv_positions)
        else:
            out = dot_product_attention(q, k, v, causal=True,
                                        q_positions=q_clamped,
                                        kv_length=pos + length,
                                        window=mha.attention_window)
    elif per_row:
        # L >= 1: every row writes its L entries at its own offsets (the
        # serving engine's decode step at L == 1, its speculative verify
        # at L == spec_len + 1) and the per-row masks score all L queries
        # in this one forward
        rows = jnp.arange(b)
        idx = pos[:, None] + jnp.arange(length)[None, :]          # (B, L)
        if rolling:
            w = cache["k"].shape[1]
            if length > 1 and w < mha.attention_window + length - 1:
                raise ValueError(
                    f"multi-token per-row steps on a rolling cache need a "
                    f"ring of >= window + L - 1 = "
                    f"{mha.attention_window + length - 1} slots, got {w} "
                    f"(init_cache(ring_slack=...)) — the oldest query's "
                    f"window would be overwritten by the newest write")
            new_cache = _kv_write(cache, (rows[:, None], idx % w), k_t, v_t)
            # slot j holds the newest position <= each row's write
            # frontier congruent to j mod w (negative = never written);
            # queries older than the frontier hide the just-written
            # future entries through the causal kv_positions comparison
            front = pos[:, None] + (length - 1)
            j = jnp.arange(w)
            kv_positions = front - jnp.mod(front - j[None, :], w)
            k, v = _kv_read(new_cache, cdtype)
            out = dot_product_attention(q, k, v, causal=True, q_offset=pos,
                                        window=mha.attention_window,
                                        kv_positions=kv_positions)
        else:
            new_cache = _kv_write(cache, (rows[:, None], idx), k_t, v_t)
            k, v = _kv_read(new_cache, cdtype)
            out = dot_product_attention(q, k, v, causal=True, q_offset=pos,
                                        kv_length=pos + length,
                                        window=mha.attention_window)
    elif rolling:
        # ring buffer of the block's window: slot p % W holds position p.
        # Single-token writes only — generate() prefills with a full cache
        # and converts (a batched ring write would wrap around the buffer).
        if length != 1:
            raise ValueError("rolling cache steps are single-token "
                             "(prefill uses a full cache, then converts)")
        w = cache["k"].shape[1]
        slot = pos % w
        k = jax.lax.dynamic_update_slice(cache["k"], k_t, (0, slot, 0, 0))
        v = jax.lax.dynamic_update_slice(cache["v"], v_t, (0, slot, 0, 0))
        # slot j currently holds position pos - ((pos - j) mod W); slots
        # not yet written come out negative and mask themselves
        j = jnp.arange(w)
        kv_positions = pos - jnp.mod(pos - j, w)
        out = dot_product_attention(q, k, v, causal=True, q_offset=pos,
                                    window=mha.attention_window,
                                    kv_positions=kv_positions)
    else:
        k = jax.lax.dynamic_update_slice(cache["k"], k_t, (0, pos, 0, 0))
        v = jax.lax.dynamic_update_slice(cache["v"], v_t, (0, pos, 0, 0))
        out = dot_product_attention(q, k, v, causal=True, q_offset=pos,
                                    kv_length=pos + length,
                                    window=mha.attention_window)
    out = out.reshape(b, length, mha.num_heads * dh)
    bias_o = params.get("bo") if mha.use_bias else None
    y = _project(out, params["wo"], bias_o, cdtype)
    return y, (new_cache if new_cache is not None else {"k": k, "v": v})


def _block_forward(block: TransformerBlock, params, x, cache, pos, cdtype,
                   rolling: bool = False,
                   paged: Optional[PagedView] = None):
    """Mirrors ``TransformerBlock.apply`` (train=False) with cached MHA."""
    ln = LayerNormalization()
    h = ln.apply(params["ln1"], x, compute_dtype=cdtype)
    h, cache = _mha_forward(block._mha(), params["attn"], h, cache, pos,
                            cdtype, rolling, paged)
    x = x + h.astype(x.dtype)
    h = ln.apply(params["ln2"], x, compute_dtype=cdtype)
    h = _project(h, params["mlp_w1"], params["mlp_b1"], cdtype)
    h = _apply_activation(block.activation, h).astype(cdtype)
    h = _project(h, params["mlp_w2"], params["mlp_b2"], cdtype)
    return x + h.astype(x.dtype), cache


def _forward(model, params, caches, toks, pos, rolling: bool = False,
             paged: Optional[PagedView] = None):
    """Walk the layer stack over (B, L) tokens starting at position
    ``pos``; returns ((B, L, V) f32 logits, new caches).  L == 1 is a
    decode step, L == P is the batched prompt prefill.  ``pos`` may be a
    (B,) per-row position vector: every row advances at its own position —
    the serving engine's mixed-length slot batch (L == 1), or its batched
    speculative verify (L == spec_len + 1, each row scoring its own L
    continuation positions in one forward).  L > 1
    batches may be right-padded to a shared length (the serving engine's
    bucketed prefill) — see ``_mha_forward`` for why the causal mask
    alone keeps pad tokens out of every real position's numerics."""
    cdtype = model._cdtype
    x = None
    new_caches: List[Any] = []
    for layer, p, cache in zip(model.layers, params, caches):
        if isinstance(layer, Embedding):
            # jnp.asarray: trained params may live as host numpy arrays
            # (FittedModel), which tracer-indexing rejects
            x = jnp.asarray(p["embedding"]).astype(cdtype)[toks]
        elif isinstance(layer, PositionalEmbedding):
            if _per_row(pos) and toks.shape[1] == 1:
                pe = jnp.asarray(p["embedding"])[pos]          # (B, D)
                x = x + pe.astype(x.dtype)[:, None]
            elif _per_row(pos):
                # per-row multi-token (the speculative verify): row r's
                # token i sits at absolute position pos[r] + i.  OOB rows
                # (a request at its very end) clamp — their logits are
                # junk the engine never commits
                idx = pos[:, None] + jnp.arange(toks.shape[1])[None, :]
                pe = jnp.asarray(p["embedding"])[idx]          # (B, L, D)
                x = x + pe.astype(x.dtype)
            else:
                pe = jax.lax.dynamic_slice_in_dim(
                    jnp.asarray(p["embedding"]), pos, toks.shape[1])
                x = x + pe.astype(x.dtype)[None]
        elif isinstance(layer, TransformerBlock):
            x, cache = _block_forward(layer, p, x, cache, pos, cdtype,
                                      rolling, paged)
        else:  # LayerNormalization / Dense: position-independent
            x = layer.apply(p, x, compute_dtype=cdtype, train=False)
        new_caches.append(cache)
    return x.astype(jnp.float32), new_caches


def decode_step(model, params, caches, tok, pos, rolling: bool = False,
                paged: Optional[PagedView] = None):
    """Advance one position.  tok: (B,) int32 current tokens; pos: scalar
    int32 position (0-based), or a (B,) int32 vector advancing every row
    at its OWN position (the serving engine's slot batch — each row writes
    its k/v at, and attends from, its own position).  ``paged``: the
    caches are a flat block arena addressed through per-row block tables
    (the serving engine's paged slot pool) — same numerics, block-granular
    storage.  Returns (logits (B, V) f32, new caches).  Jittable — wrap
    in ``jax.jit`` (or let ``generate`` do it) for real use;
    ``jit_decode_step`` packages exactly that."""
    logits, caches = _forward(model, params, caches, tok[:, None], pos,
                              rolling, paged)
    return logits[:, 0], caches


def jit_decode_step(model, rolling: bool = False):
    """The jitted single-token entry point for serving loops that own their
    own sampling/stopping logic (``generate`` builds its scan from the same
    ``decode_step``, so numerics are identical).

    Returns ``step(params, caches, tok, pos) -> (logits (B, V) f32,
    new caches)`` compiled once per (batch, cache-length) shape::

        caches = init_cache(model, batch, max_len)
        step = jit_decode_step(model)
        for pos in range(p_len, max_len):
            logits, caches = step(params, caches, tok, pos)
            tok = my_sampler(logits)

    ``model`` and ``rolling`` are closed over (they shape the program);
    ``pos`` is a traced argument, so advancing it does NOT recompile.
    """
    _check_supported(model)
    if rolling:
        _validate_rolling(model)

    @jax.jit
    def step(params, caches, tok, pos):
        return decode_step(model, params, caches,
                           jnp.asarray(tok, jnp.int32), pos, rolling)

    return step


def _validate_sampling(temperature: float, rng,
                       top_k: Optional[int], top_p: Optional[float]):
    """The one sampling-surface rule set, shared by ``generate`` and
    ``speculative_generate``."""
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature > 0 sampling needs rng")
    if top_k is not None or top_p is not None:
        if temperature <= 0.0:
            raise ValueError(
                "top_k/top_p shape the SAMPLING distribution — pass "
                "temperature > 0 (greedy argmax ignores them)")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def _validate_stopping(eos_id: Optional[int], pad_id: Optional[int],
                       vocab: Optional[int]):
    """The one eos_id/pad_id rule set, shared by ``generate`` and
    ``beam_search``.  Out-of-range ids would be silently clamped by the
    ``.at[].set`` scatter and the embedding gather — refuse instead."""
    if pad_id is not None and eos_id is None:
        raise ValueError("pad_id only means something with eos_id")
    if eos_id is not None and vocab is not None \
            and not 0 <= eos_id < vocab:
        raise ValueError(f"eos_id {eos_id} outside the model's vocabulary "
                         f"[0, {vocab}) — stopping could never trigger")
    if pad_id is not None and vocab is not None \
            and not 0 <= pad_id < vocab:
        raise ValueError(f"pad_id {pad_id} outside the model's vocabulary "
                         f"[0, {vocab})")


def _filter_logits(logits, top_k: Optional[int], top_p: Optional[float]):
    """Restrict a (B, V) logit row to the top-k tokens and/or the smallest
    nucleus whose probability mass reaches top_p (the top token always
    survives); filtered entries go to -inf.  k-then-p order, the standard
    composition."""
    if top_k is not None:
        k = min(int(top_k), logits.shape[-1])  # k past vocab = keep all
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose preceding cumulative mass is < top_p (the top
        # token's is 0, so at least one survives); the cut logit is the
        # smallest kept one
        kept = jnp.sum((cum - probs) < top_p, axis=-1, keepdims=True)
        cut = jnp.take_along_axis(sorted_desc, kept - 1, axis=-1)
        logits = jnp.where(logits < cut, -jnp.inf, logits)
    return logits


def sample_logits(logits, pos, temperature: float = 0.0,
                  rng: Optional[jax.Array] = None,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None) -> jnp.ndarray:
    """The ONE per-step sampling rule: (B, V) f32 logits at absolute
    position ``pos`` → (B,) int32 next tokens.  temperature 0 = greedy
    argmax; > 0 = softmax sampling after ``_filter_logits`` warping, with
    the step key derived as ``fold_in(rng, pos)`` so a position's draw is
    a pure function of (rng, pos).  ``generate`` samples through exactly
    this function, and the serving engine reuses it for per-request
    prefill sampling — the two paths cannot drift."""
    if temperature > 0.0:
        step_rng = jax.random.fold_in(rng, pos)
        logits = _filter_logits(logits / temperature, top_k, top_p)
        nxt = jax.random.categorical(step_rng, logits)
    else:
        nxt = jnp.argmax(logits, axis=-1)
    return nxt.astype(jnp.int32)


def filter_logits_batched(logits, top_k, top_p):
    """Per-row ``_filter_logits`` with TRACED per-row parameters: ``top_k``
    (B,) int32 (0 = disabled), ``top_p`` (B,) f32 (0 = disabled).  Row r
    with ``top_k[r] == K > 0`` and ``top_p[r] == P > 0`` computes exactly
    what ``_filter_logits(row, K, P)`` computes (the k-th value comes from
    a descending sort instead of ``lax.top_k`` — the same exact selection —
    and the k-then-p composition order is preserved), so one jitted program
    serves a slot batch with heterogeneous sampling configs."""
    v = logits.shape[-1]
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    k = jnp.clip(top_k, 1, v)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    logits = jnp.where((top_k > 0)[:, None] & (logits < kth),
                       -jnp.inf, logits)
    # p filter runs on the k-filtered logits (k-then-p, as _filter_logits)
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    kept = jnp.sum((cum - probs) < top_p[:, None], axis=-1, keepdims=True)
    cut = jnp.take_along_axis(sorted_desc, jnp.maximum(kept, 1) - 1, axis=-1)
    return jnp.where((top_p > 0)[:, None] & (logits < cut),
                     -jnp.inf, logits)


def sample_logits_batched(logits, positions, temperature, rngs,
                          top_k, top_p) -> jnp.ndarray:
    """Per-row ``sample_logits``: every row carries its own sampling config.

    ``positions`` (B,) int32 absolute positions; ``temperature`` (B,) f32
    (<= 0 = greedy argmax for that row); ``rngs`` (B, 2) uint32 per-row base
    keys (each folded by its row's position, exactly as ``sample_logits``
    folds the shared key); ``top_k``/``top_p`` as in
    ``filter_logits_batched``.  Row-for-row this reproduces
    ``sample_logits`` on that row's scalar params — vmapped ``fold_in`` +
    ``categorical`` draw the same counter-based random bits as the
    unbatched calls, which is what makes the serving engine's output
    bit-identical to offline ``generate``."""
    temp = jnp.asarray(temperature, jnp.float32)
    safe = jnp.where(temp > 0.0, temp, 1.0)
    warped = filter_logits_batched(logits / safe[:, None], top_k, top_p)
    keys = jax.vmap(jax.random.fold_in)(rngs, positions)
    sampled = jax.vmap(jax.random.categorical)(keys, warped)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(temp > 0.0, sampled, greedy).astype(jnp.int32)


def _to_ring(full_cache, p_len: int, window: int):
    """Convert a full prefill cache (positions 0..p_len-1 at slots
    0..p_len-1) into a W-slot ring where slot ``p % W`` holds position
    ``p``, keeping the last ``window`` positions."""
    if p_len >= window:
        # entries for positions p0..p_len-1 (p0 = p_len - W), in order;
        # rolling by p0 % W puts position p at slot p % W
        p0 = p_len - window
        last = jax.lax.dynamic_slice_in_dim(full_cache, p0, window, axis=1)
        return jnp.roll(last, p0 % window, axis=1)
    # shorter prompt: positions 0..p_len-1 already sit at their slots;
    # grow/trim to W slots (unwritten tail masks itself via kv_positions)
    pad = window - full_cache.shape[1]
    if pad > 0:
        zeros = jnp.zeros(full_cache.shape[:1] + (pad,)
                          + full_cache.shape[2:], full_cache.dtype)
        return jnp.concatenate([full_cache, zeros], axis=1)
    return full_cache[:, :window]


def ring_from_prefill(full_cache, p_lens, window: int):
    """Traced, per-row ``_to_ring``: (B, S, H, D) full prefill cache rows →
    (B, W, H, D) rings where slot ``p % W`` holds position ``p``, keeping
    each row's last ``window`` prompt positions.  ``p_lens`` is a (B,)
    TRACED vector of true prompt lengths (the serving engine's bucketed
    prefill converts a whole mixed-length batch in one jitted program);
    slots a short row never wrote come out zero, exactly like
    ``_to_ring``'s zero tail (they self-mask through ``kv_positions`` at
    decode time).  Row-for-row this gathers the same entries ``_to_ring``
    copies — it is a pure relayout, bit-identical by construction."""
    w = int(window)
    j = jnp.arange(w)
    p = jnp.reshape(jnp.asarray(p_lens, jnp.int32), (-1, 1))      # (B, 1)
    # ring slot j holds the newest prompt position congruent to j mod W;
    # rows shorter than W leave their tail slots negative (= never written)
    q = (p - 1) - jnp.mod(p - 1 - j[None, :], w)                  # (B, W)
    src = jnp.clip(q, 0, full_cache.shape[1] - 1)
    rows = jnp.take_along_axis(full_cache, src[:, :, None, None], axis=1)
    return jnp.where((q >= 0)[:, :, None, None], rows,
                     jnp.zeros((), full_cache.dtype))


def generate(model, params, prompt, num_steps: int,
             temperature: float = 0.0, rng: Optional[jax.Array] = None,
             max_len: Optional[int] = None,
             rolling: bool = False,
             top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             eos_id: Optional[int] = None,
             pad_id: Optional[int] = None) -> jnp.ndarray:
    """Continue ``prompt`` (B, P) int tokens by ``num_steps`` tokens.

    temperature 0 = greedy argmax; > 0 = softmax sampling (needs ``rng``).
    ``top_k`` / ``top_p`` (sampling only) restrict each step's distribution
    to the k highest-logit tokens and/or the smallest nucleus reaching
    probability mass ``top_p`` before drawing — combinable (k first, then
    p, the standard composition).
    ``eos_id``: once a sequence emits it, every later slot in that row is
    ``pad_id`` (default: ``eos_id`` itself) — per-row stopping for batched
    serving; the output stays the static (B, P + num_steps) shape.
    Returns (B, P + num_steps) tokens.  Prefill is one batched forward;
    the continuation is one compiled ``lax.scan`` of single-token steps.

    ``rolling=True`` (sliding-window models): after the prefill, each
    block's cache collapses to a ring of its ``attention_window`` slots,
    so generation memory is O(W) regardless of ``num_steps`` — identical
    tokens to ``rolling=False`` (windowed attention never looks past W).
    """
    _check_supported(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    if num_steps < 0:
        raise ValueError(f"num_steps must be >= 0, got {num_steps}")
    total = p_len + int(num_steps)
    if max_len is None:
        max_len = total
    if max_len < total:
        raise ValueError(f"max_len {max_len} < prompt+steps {total}")
    limit = _context_limit(model)
    if limit is not None and total > limit:
        raise ValueError(
            f"prompt ({p_len}) + num_steps ({num_steps}) = {total} exceeds "
            f"the model's positional-embedding range {limit}")
    _validate_sampling(temperature, rng, top_k, top_p)
    _validate_stopping(eos_id, pad_id, _vocab_size(model))
    if rolling:
        # the prefill below still uses a full P-slot cache (one batched
        # forward), which then collapses to rings — peak memory O(P + W),
        # steady-state O(W)
        _validate_rolling(model)
    if num_steps == 0:
        # after validation, so invalid argument combinations fail the same
        # way regardless of step count
        return prompt
    caches = init_cache(model, b, p_len if rolling else max_len)

    def sample(logits, pos):
        return sample_logits(logits, pos, temperature, rng, top_k, top_p)

    # prefill: all P prompt positions in one batched forward
    logits, caches = _forward(model, params, caches, prompt, 0)
    first = sample(logits[:, -1], p_len - 1)

    if rolling:
        ringed = []
        for layer, cache in zip(model.layers, caches):
            if cache is None:
                ringed.append(None)
                continue
            w = layer._mha().attention_window
            ringed.append({name: _to_ring(cache[name], p_len, w)
                           for name in ("k", "v")})
        caches = ringed

    pad = jnp.int32(pad_id if pad_id is not None else (eos_id or 0))

    def body(carry, i):
        caches, tok, done = carry
        pos = p_len + i
        logits, caches = decode_step(model, params, caches, tok, pos,
                                     rolling)
        nxt = sample(logits, pos)
        if eos_id is not None:
            # rows whose CURRENT token is eos (or that finished earlier)
            # emit padding from the next slot on
            done = done | (tok == eos_id)
            nxt = jnp.where(done, pad, nxt)
        return (caches, nxt, done), tok

    done0 = jnp.zeros((b,), bool)
    (caches, last, _), toks = jax.lax.scan(
        body, (caches, first, done0), jnp.arange(int(num_steps) - 1))
    gen = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1) \
        if num_steps > 1 else first[:, None]
    return jnp.concatenate([prompt, gen], axis=1)


def speculative_generate(model, params, draft_model, draft_params, prompt,
                         num_steps: int, draft_len: int = 4,
                         max_len: Optional[int] = None,
                         temperature: float = 0.0,
                         rng: Optional[jax.Array] = None,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         eos_id: Optional[int] = None,
                         pad_id: Optional[int] = None,
                         return_stats: bool = False):
    """Decoding accelerated by a cheaper draft model — distribution-exact.

    ``temperature == 0`` (default): greedy-exact — every committed token is
    the TARGET's own argmax, whatever the draft proposes.  (The argmax
    comes from the batched verify forward; it can differ from single-token
    ``generate`` only where two logits tie to within the fusion-order
    rounding between an L-token and a 1-token program — measure-zero for
    trained models, asserted bit-identical across this suite's CI models
    and drafts.)

    ``temperature > 0`` (needs ``rng``): SPECULATIVE SAMPLING (Leviathan
    et al. 2022 / Chen et al. 2023 rejection rule).  Both distributions
    are first warped identically (temperature, then ``top_k``/``top_p``
    as in ``generate``); each drafted token x ~ q is accepted with
    probability min(1, p(x)/q(x)), and the first rejection draws from the
    residual norm(max(p − q, 0)).  The committed-token distribution is
    EXACTLY the warped target distribution — the draft changes wall-clock
    only, never statistics (asserted against closed-form marginals in
    tests/test_speculative.py).

    Each round the draft proposes ``draft_len`` tokens one at a time; the
    target then scores ALL of them in ONE batched forward (the MXU-shaped
    win: k positions per target call instead of 1) and commits the
    accepted prefix plus one bonus/correction token.  A good draft commits
    ``draft_len + 1`` tokens per target call; a useless draft still
    commits 1.

    No cache rollback is needed on rejection: rejected positions hold
    stale k/v, but every attention in this walker masks slots ``>=
    kv_length``, and the next round overwrites them before they can be
    unmasked.  Batched prompts commit the MINIMUM accepted length across
    rows (greedy: every committed token is the target's own argmax for
    every row; sampling: truncating a row's accepted run early never
    conditions on later randomness — exactness holds row-wise either way).

    Both models must share the vocabulary.  ``eos_id``/``pad_id`` behave
    exactly as in ``generate``: once a row emits eos, its later slots are
    ``pad_id`` (default: the eos itself), the output keeps its static
    shape — and a batch whose EVERY row has finished stops issuing
    draft/verify calls entirely (the speculative serving win compounds).
    ``return_stats=True`` additionally returns ``{"target_calls",
    "drafted", "accepted"}`` — ``target_calls`` counts the decode-phase
    verify forwards (the prompt prefill is one more target forward on
    top).
    """
    _check_supported(model)
    _check_supported(draft_model)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    if num_steps < 1:
        raise ValueError(f"speculative_generate needs num_steps >= 1, got "
                         f"{num_steps}")
    if draft_len < 1:
        raise ValueError(f"draft_len must be >= 1, got {draft_len}")
    _validate_sampling(temperature, rng, top_k, top_p)
    tv, dv = _vocab_size(model), _vocab_size(draft_model)
    _validate_stopping(eos_id, pad_id, tv)
    if tv is not None and dv is not None and tv != dv:
        raise ValueError(f"target and draft vocabularies differ: {tv} vs "
                         f"{dv} — argmax agreement would be meaningless")
    total = p_len + int(num_steps)
    if max_len is None:
        max_len = total
    if max_len < total:
        raise ValueError(f"max_len {max_len} < prompt+steps {total}")
    for name, m in (("target", model), ("draft", draft_model)):
        limit = _context_limit(m)
        if limit is not None and total > limit:
            raise ValueError(
                f"prompt + num_steps = {total} exceeds the {name} model's "
                f"positional-embedding range {limit}")

    # allocate draft_len slots of slack so every round can draft and
    # verify at the SAME (B, draft_len + 1) shape — without it the tail
    # rounds shrink k and each distinct width pays a fresh XLA compile.
    # Slack slots only ever hold discarded writes (kv_length-masked);
    # learned-positional models cap the slack at their trained range and
    # may shrink on the final rounds.
    def alloc_for(m):
        limit = _context_limit(m)
        want = max_len + int(draft_len)
        return want if limit is None else min(want, limit)

    t_caches = init_cache(model, b, alloc_for(model))
    d_caches = init_cache(draft_model, b, alloc_for(draft_model))
    alloc = min(alloc_for(model), alloc_for(draft_model))
    logits, t_caches = _forward(model, params, t_caches, prompt, 0)
    _, d_caches = _forward(draft_model, draft_params, d_caches, prompt, 0)

    sampled = temperature > 0.0

    def warp(l):
        # identical warp for target and draft — the rejection rule is
        # exact for whatever pair of distributions it compares, so
        # warping both reproduces plain warped-target sampling
        return _filter_logits(l / temperature, top_k, top_p) if sampled \
            else l

    _draw = [0]  # host-side draw counter -> a fresh fold per random draw

    def _key():
        _draw[0] += 1
        return jax.random.fold_in(rng, _draw[0])

    if sampled:
        cur = jax.random.categorical(
            _key(), warp(logits[:, -1])).astype(jnp.int32)
    else:
        cur = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)  # (B,)

    # model closes over (it shapes the program); params stay a traced arg.
    # caches are donated: every call rebinds t_caches to the output and the
    # input buffer is dead — rejection never rolls back (rejected positions
    # are simply overwritten by the next round), so no alias survives
    verify = jax.jit(lambda p, caches, toks, pos: _forward(
        model, p, caches, toks, pos), donate_argnums=(1,))
    d_step = jit_decode_step(draft_model)

    # eos stopping, same semantics as generate: a row that emitted eos
    # gets pad in every later slot.  Applied per COMMITTED token in commit
    # order, so it composes with both the greedy and the sampled rule
    # (padding is a row-wise post-map; exactness is untouched).
    pad_tok = jnp.int32(pad_id if pad_id is not None else (eos_id or 0))
    done = jnp.zeros((b,), bool)
    out = []

    def commit(tok):
        nonlocal done
        if eos_id is not None:
            tok = jnp.where(done, pad_tok, tok)
            done = done | (tok == eos_id)
        out.append(tok)

    commit(cur)
    cur = out[-1]
    pos = p_len - 1  # cur continues from here; its cache slot is pos + 1
    stats = {"target_calls": 0, "drafted": 0, "accepted": 0}
    while len(out) < num_steps:
        if eos_id is not None and bool(jnp.all(done)):
            # every row finished: no more draft/verify calls — fill the
            # remaining slots with one shared pad row and stop
            pad_row = jnp.full((b,), pad_tok, jnp.int32)
            out.extend([pad_row] * (num_steps - len(out)))
            break
        # fixed k = draft_len whenever the allocation allows (one compiled
        # verify shape); the commit clamp below keeps outputs exact even
        # when more is drafted than remains to emit
        k = max(min(int(draft_len), alloc - (pos + 1) - 1), 0)
        # draft k tokens from cur (argmax, or a sample from warped q)
        d_toks, q_logits = [], []
        tok = cur
        for i in range(k):
            dl, d_caches = d_step(draft_params, d_caches, tok, pos + 1 + i)
            wl = warp(dl)
            tok = (jax.random.categorical(_key(), wl) if sampled
                   else jnp.argmax(dl, axis=-1)).astype(jnp.int32)
            d_toks.append(tok)
            q_logits.append(wl)
        # one target forward over [cur, d_1 .. d_k] (L = k + 1): logits[i]
        # scores the token FOLLOWING fed[i], so a fully-accepted round
        # still has a bonus logit at index k
        fed = jnp.stack([cur] + d_toks, axis=1)               # (B, k + 1)
        logits, t_caches = verify(params, t_caches, fed, pos + 1)
        stats["target_calls"] += 1
        stats["drafted"] += k
        if k == 0:
            nxt = (jax.random.categorical(_key(), warp(logits[:, 0]))
                   if sampled else jnp.argmax(logits[:, 0], axis=-1))
            commit(nxt.astype(jnp.int32))
            cur = out[-1]
            pos += 1
            continue
        drafted = jnp.stack(d_toks, axis=1)                   # (B, k)
        if sampled:
            # rejection rule: accept x ~ q with prob min(1, p(x)/q(x));
            # the first rejection redraws from norm(max(p - q, 0))
            p = jax.nn.softmax(warp(logits[:, :k]), axis=-1)  # (B, k, V)
            q = jax.nn.softmax(jnp.stack(q_logits, axis=1), axis=-1)
            px = jnp.take_along_axis(
                p, drafted[..., None], axis=-1)[..., 0]       # (B, k)
            qx = jnp.take_along_axis(q, drafted[..., None], axis=-1)[..., 0]
            u = jax.random.uniform(_key(), (b, k))
            accept = u * jnp.maximum(qx, 1e-30) < px          # u < p/q
            prefix = jnp.cumprod(accept.astype(jnp.int32), axis=1)
            n_row = jnp.sum(prefix, axis=1)                   # (B,)
            a = int(jnp.min(n_row))
            a = min(a, num_steps - len(out) - 1)
            for i in range(a):
                commit(drafted[:, i])         # accepted by every row
            if a == k:
                # fully accepted: bonus token straight from warped p
                tok_a = jax.random.categorical(
                    _key(), warp(logits[:, k])).astype(jnp.int32)
            else:
                res = jnp.maximum(p[:, a] - q[:, a], 0.0)
                rsum = jnp.sum(res, axis=-1, keepdims=True)
                # res == 0 iff p <= q everywhere, i.e. p == q: fall back
                res = jnp.where(rsum > 0.0, res / jnp.maximum(rsum, 1e-38),
                                p[:, a])
                rej = jax.random.categorical(
                    _key(), jnp.log(jnp.maximum(res, 1e-38)))
                # rows that accepted position a keep their drafted token
                # (truncation never conditions on later randomness)
                tok_a = jnp.where(n_row > a, drafted[:, a],
                                  rej).astype(jnp.int32)
            commit(tok_a)
        else:
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            match = drafted == greedy[:, :k]                  # (B, k)
            # per-row accepted prefix length; commit the batch minimum
            prefix = jnp.cumprod(match.astype(jnp.int32), axis=1)
            a = int(jnp.min(jnp.sum(prefix, axis=1)))
            a = min(a, num_steps - len(out) - 1)
            for i in range(a):
                commit(greedy[:, i])          # == accepted draft tokens
            commit(greedy[:, a])              # bonus / correction token
        stats["accepted"] += a
        cur = out[-1]
        pos += a + 1
        if a == k and len(out) < num_steps:
            # fully-accepted round: d_k was committed (position pos, the
            # new continuation point) but never FED to the draft, so its
            # draft-cache slot would stay a zero hole inside every later
            # step's attended range, quietly eroding draft quality.  One
            # catch-up step writes it (logits discarded).
            _, d_caches = d_step(draft_params, d_caches, drafted[:, -1],
                                 pos)

    gen = jnp.stack(out[:num_steps], axis=1)
    result = jnp.concatenate([prompt, gen], axis=1)
    return (result, stats) if return_stats else result


def beam_search(model, params, prompt, num_steps: int, num_beams: int = 4,
                length_penalty: float = 0.0,
                eos_id: Optional[int] = None,
                pad_id: Optional[int] = None):
    """Deterministic beam decoding: keep the ``num_beams`` highest
    log-probability continuations of each prompt row.

    prompt: (B, P) int tokens → ``(tokens (B, num_beams, P + num_steps),
    scores (B, num_beams))``, beams sorted best-first.  Scores are summed
    token log-probabilities; ``length_penalty`` alpha > 0 divides by
    ``generated_length ** alpha`` before the final ranking (alpha = 0:
    pure sum, favors short sequences when ``eos_id`` is set).

    ``eos_id``: a beam that emits it is FINISHED — its score freezes, its
    later slots fill with ``pad_id`` (default: the eos itself), and it
    keeps competing against live beams at the frozen score.  The KV caches
    ride at batch B·num_beams and are re-gathered to each step's surviving
    parents, so memory is ``num_beams``× a greedy ``generate``.

    Beam 0 with ``num_beams=1`` is exactly greedy ``generate`` (asserted
    in tests); rolling-window caches are not supported here (beam
    reordering and ring slots don't compose yet — use ``generate``).
    """
    _check_supported(model)
    prompt = jnp.asarray(prompt, jnp.int32)
    b, p_len = prompt.shape
    k = int(num_beams)
    if k < 1:
        raise ValueError(f"num_beams must be >= 1, got {num_beams}")
    if num_steps < 1:
        raise ValueError(f"beam_search needs num_steps >= 1, got "
                         f"{num_steps}")
    if length_penalty < 0:
        raise ValueError(f"length_penalty must be >= 0, got "
                         f"{length_penalty}")
    total = p_len + int(num_steps)
    limit = _context_limit(model)
    if limit is not None and total > limit:
        raise ValueError(
            f"prompt ({p_len}) + num_steps ({num_steps}) = {total} exceeds "
            f"the model's positional-embedding range {limit}")
    vocab = _vocab_size(model)
    _validate_stopping(eos_id, pad_id, vocab)
    pad = jnp.int32(pad_id if pad_id is not None else (eos_id or 0))

    # prefill once at batch B, then tile every cache to B·k rows laid out
    # row-major (batch, beam) — beam j of row i lives at i·k + j
    caches = init_cache(model, b, total)
    logits, caches = _forward(model, params, caches, prompt, 0)
    logp0 = jax.nn.log_softmax(logits[:, -1], axis=-1)        # (B, V)
    v = logp0.shape[-1]
    scores, first = jax.lax.top_k(logp0, k)                   # (B, k)
    first = first.astype(jnp.int32)
    caches = tmap(lambda c: jnp.repeat(c, k, axis=0), caches)
    done = (first == eos_id) if eos_id is not None \
        else jnp.zeros((b, k), bool)

    # candidate row for a finished beam: only the pad column, at +0 — the
    # beam's score freezes but it stays in the running
    frozen = jnp.full((v,), -jnp.inf).at[pad].set(0.0)

    def body(carry, i):
        caches, scores, tok, done = carry
        pos = p_len + i
        logits, caches = decode_step(model, params, caches,
                                     tok.reshape(b * k), pos)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(b, k, v)
        logp = jnp.where(done[..., None], frozen, logp)
        cand = (scores[..., None] + logp).reshape(b, k * v)
        scores, idx = jax.lax.top_k(cand, k)                  # (B, k)
        parent = idx // v
        nxt = (idx % v).astype(jnp.int32)
        flat_parent = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
        caches = tmap(lambda c: jnp.take(c, flat_parent, axis=0), caches)
        done = jnp.take_along_axis(done, parent, axis=1)
        if eos_id is not None:
            nxt = jnp.where(done, pad, nxt)
            done = done | (nxt == eos_id)
        return (caches, scores, nxt, done), (nxt, parent)

    (caches, scores, last, done), (toks, parents) = jax.lax.scan(
        body, (caches, scores, first, done),
        jnp.arange(int(num_steps) - 1))

    # reconstruct each surviving beam's token path by walking the parent
    # pointers backward from the final beam order
    steps = int(num_steps)
    tokens = jnp.zeros((b, k, steps), jnp.int32)
    beam = jnp.broadcast_to(jnp.arange(k), (b, k))            # final slots
    for i in range(steps - 1, 0, -1):
        tokens = tokens.at[:, :, i].set(
            jnp.take_along_axis(toks[i - 1], beam, axis=1))
        beam = jnp.take_along_axis(parents[i - 1], beam, axis=1)
    tokens = tokens.at[:, :, 0].set(
        jnp.take_along_axis(first, beam, axis=1))

    if length_penalty > 0:
        if eos_id is not None:
            hit = tokens == eos_id
            first_eos = jnp.argmax(hit, axis=-1)
            lengths = jnp.where(hit.any(axis=-1), first_eos + 1, steps)
        else:
            lengths = jnp.full((b, k), steps)
        ranked = scores / (lengths.astype(jnp.float32) ** length_penalty)
    else:
        ranked = scores
    order = jnp.argsort(-ranked, axis=-1)
    tokens = jnp.take_along_axis(tokens, order[..., None], axis=1)
    ranked = jnp.take_along_axis(ranked, order, axis=1)
    out = jnp.concatenate(
        [jnp.broadcast_to(prompt[:, None], (b, k, p_len)), tokens], axis=2)
    return out, ranked
