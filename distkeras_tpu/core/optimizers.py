"""Optimizers: Keras-style names/constructors backed by optax.

The reference hands trainers a Keras *worker optimizer* by name or object
(reference: ``distkeras/trainers.py :: Trainer.__init__(..., worker_optimizer)``
compiled per worker in ``workers.py``).  We accept the same spelling —
``'adagrad'``, ``'adam'``, ``'sgd'``, ... or an ``Optimizer`` instance — and
back each with the corresponding optax gradient transformation, which jit/scan
cleanly and shard trivially under SPMD.

BatchNormalization running stats live in the params pytree under a ``"stats"``
key; ``build()`` masks them out of the optimizer update so they are carried,
not trained.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import optax


class Optimizer:
    """Thin named wrapper over an optax transformation factory."""

    def __init__(self, name: str, **hyper):
        self.name = name
        self.hyper = hyper

    def to_optax(self) -> optax.GradientTransformation:
        h = self.hyper
        lr = h.get("learning_rate", _DEFAULT_LR.get(self.name, 0.01))
        if self.name == "sgd":
            return optax.sgd(lr, momentum=h.get("momentum", 0.0),
                             nesterov=h.get("nesterov", False))
        if self.name == "adam":
            return optax.adam(lr, b1=h.get("beta_1", 0.9),
                              b2=h.get("beta_2", 0.999),
                              eps=h.get("epsilon", 1e-7))
        if self.name == "adamw":
            return optax.adamw(lr, b1=h.get("beta_1", 0.9),
                               b2=h.get("beta_2", 0.999),
                               eps=h.get("epsilon", 1e-7),
                               weight_decay=h.get("weight_decay", 1e-4))
        if self.name == "adagrad":
            return optax.adagrad(lr, eps=h.get("epsilon", 1e-7))
        if self.name == "adadelta":
            return optax.adadelta(lr, rho=h.get("rho", 0.95),
                                  eps=h.get("epsilon", 1e-7))
        if self.name == "rmsprop":
            return optax.rmsprop(lr, decay=h.get("rho", 0.9),
                                 eps=h.get("epsilon", 1e-7),
                                 momentum=h.get("momentum", 0.0))
        if self.name == "nadam":
            return optax.nadam(lr, b1=h.get("beta_1", 0.9),
                               b2=h.get("beta_2", 0.999),
                               eps=h.get("epsilon", 1e-7))
        if self.name == "adamax":
            return optax.adamax(lr, b1=h.get("beta_1", 0.9),
                                b2=h.get("beta_2", 0.999),
                                eps=h.get("epsilon", 1e-7))
        if self.name == "lamb":
            return optax.lamb(lr)
        if self.name == "lion":
            # sign-momentum optimizer (Chen et al. 2023): ~3-10x smaller
            # typical lr than adam, one moment buffer instead of two
            return optax.lion(lr, b1=h.get("beta_1", 0.9),
                              b2=h.get("beta_2", 0.99),
                              weight_decay=h.get("weight_decay", 0.0))
        raise ValueError(f"Unknown optimizer {self.name!r}")

    def get_config(self):
        return {"name": self.name, **self.hyper}

    def __repr__(self):
        return f"Optimizer({self.name!r}, {self.hyper})"


_DEFAULT_LR = {
    "sgd": 0.01,
    "adam": 0.001,
    "adamw": 0.001,
    "adagrad": 0.01,
    "adadelta": 1.0,
    "rmsprop": 0.001,
    "nadam": 0.002,   # Keras-1.x Nadam/Adamax default lr
    "adamax": 0.002,
    "lamb": 0.001,
    "lion": 0.0001,
}

# full Keras-1.x name set resolves to true optax counterparts (the 2016
# reference accepted any Keras optimizer string through worker_optimizer)
_ALIASES = {}


def SGD(learning_rate=0.01, momentum=0.0, nesterov=False):
    return Optimizer("sgd", learning_rate=learning_rate, momentum=momentum,
                     nesterov=nesterov)


def Adam(learning_rate=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-7):
    return Optimizer("adam", learning_rate=learning_rate, beta_1=beta_1,
                     beta_2=beta_2, epsilon=epsilon)


def Adagrad(learning_rate=0.01, epsilon=1e-7):
    return Optimizer("adagrad", learning_rate=learning_rate, epsilon=epsilon)


def Adadelta(learning_rate=1.0, rho=0.95, epsilon=1e-7):
    return Optimizer("adadelta", learning_rate=learning_rate, rho=rho,
                     epsilon=epsilon)


def RMSprop(learning_rate=0.001, rho=0.9, epsilon=1e-7, momentum=0.0):
    return Optimizer("rmsprop", learning_rate=learning_rate, rho=rho,
                     epsilon=epsilon, momentum=momentum)


def get_optimizer(spec: Any, learning_rate: Optional[float] = None) -> Optimizer:
    """Resolve a Keras-style optimizer spec: name string or Optimizer."""
    if isinstance(spec, Optimizer):
        return spec
    if isinstance(spec, str):
        name = _ALIASES.get(spec.lower(), spec.lower())
        hyper = {}
        if learning_rate is not None:
            hyper["learning_rate"] = learning_rate
        return Optimizer(name, **hyper)
    raise TypeError(f"Cannot interpret optimizer spec {spec!r}")


def get_schedule(spec: Any, base_lr: float,
                 total_steps: Optional[int] = None):
    """Resolve an LR-schedule spec to an optax schedule callable.

    ``spec``: None (returns ``base_lr`` unchanged), a callable
    (step -> lr, used as-is), a name string, or a ``{"name": ..., ...}``
    dict overriding the defaults.  Named schedules:

    - ``"warmup_cosine"``: linear 0 → ``base_lr`` over ``warmup_steps``
      (default 10% of ``total_steps``), cosine decay to ``end_value``
      (default 0) over ``decay_steps`` (default ``total_steps``).
    - ``"cosine"``: cosine decay ``base_lr`` → ``alpha * base_lr`` over
      ``decay_steps``.
    - ``"constant"``: ``base_lr`` forever (explicit no-op).

    ``total_steps`` is the trainer's optimizer-update count (it knows the
    epoch/round geometry); required for the defaults above.
    """
    if spec is None:
        return base_lr
    if callable(spec):
        return spec
    if isinstance(spec, str):
        spec = {"name": spec}
    if not isinstance(spec, dict) or "name" not in spec:
        raise TypeError(
            f"lr_schedule must be a name, {{'name': ...}} dict or callable, "
            f"got {spec!r}")
    cfg = dict(spec)
    name = cfg.pop("name")
    if name == "constant":
        if cfg:
            raise ValueError(f"unknown lr_schedule keys {sorted(cfg)}")
        return base_lr
    decay_steps = cfg.pop("decay_steps", total_steps)
    if decay_steps is None:
        raise ValueError(
            f"lr_schedule {name!r} needs decay_steps (or a trainer that "
            "knows its total step count)")
    import optax as _optax
    if name == "warmup_cosine":
        warmup = cfg.pop("warmup_steps", max(int(decay_steps * 0.1), 1))
        sched = _optax.warmup_cosine_decay_schedule(
            init_value=cfg.pop("init_value", 0.0), peak_value=base_lr,
            warmup_steps=int(warmup), decay_steps=int(decay_steps),
            end_value=cfg.pop("end_value", 0.0))
    elif name == "cosine":
        sched = _optax.cosine_decay_schedule(
            init_value=base_lr, decay_steps=int(decay_steps),
            alpha=cfg.pop("alpha", 0.0))
    else:
        raise ValueError(f"unknown lr_schedule {name!r} "
                         "(warmup_cosine/cosine/constant)")
    if cfg:
        raise ValueError(f"unknown lr_schedule keys {sorted(cfg)}")
    return sched


def _trainable_mask(params):
    """Pytree mask: False for BatchNorm running ``stats`` subtrees."""
    def mask_layer(p):
        if isinstance(p, dict):
            return {k: (False if k == "stats"
                        else jax.tree_util.tree_map(lambda _: True, v))
                    for k, v in p.items()}
        return jax.tree_util.tree_map(lambda _: True, p)
    return [mask_layer(p) for p in params]


def build_tx(spec: Any, params, learning_rate: Optional[float] = None,
             lr_schedule: Any = None, total_steps: Optional[int] = None,
             gradient_accumulation: int = 1,
             gradient_clip_norm: Optional[float] = None
             ) -> optax.GradientTransformation:
    """Build the optax transformation for a params pytree: optional
    global-norm clip → named optimizer (optionally LR-scheduled) →
    non-trainable masking → optional gradient accumulation
    (``optax.MultiSteps`` averaging ``gradient_accumulation`` mini-step
    gradients per real update — the large-batch knob when one batch no
    longer fits HBM).  ``gradient_clip_norm`` rescales each update's
    gradients so their global L2 norm never exceeds it (the standard
    transformer training stabilizer)."""
    opt = get_optimizer(spec, learning_rate)
    if lr_schedule is not None:
        base = opt.hyper.get("learning_rate",
                             _DEFAULT_LR.get(opt.name, 0.01))
        opt = Optimizer(opt.name, **{
            **opt.hyper,
            "learning_rate": get_schedule(lr_schedule, base, total_steps)})
    inner = opt.to_optax()
    if gradient_clip_norm is not None:
        if gradient_clip_norm <= 0:
            raise ValueError(
                f"gradient_clip_norm must be > 0, got {gradient_clip_norm}")
        inner = optax.chain(
            optax.clip_by_global_norm(float(gradient_clip_norm)), inner)
    tx = optax.masked(inner, _trainable_mask(params))
    k = int(gradient_accumulation)
    if k < 1:
        raise ValueError(f"gradient_accumulation must be >= 1, got {k}")
    if k > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=k
                              ).gradient_transformation()
    return tx


def build(spec: Any, params, learning_rate: Optional[float] = None,
          lr_schedule: Any = None, total_steps: Optional[int] = None,
          gradient_accumulation: int = 1,
          gradient_clip_norm: Optional[float] = None):
    """Build (optax_tx, opt_state) for a params pytree, masking non-trainables.

    Returns the transformation and its initialized state.
    """
    tx = build_tx(spec, params, learning_rate, lr_schedule, total_steps,
                  gradient_accumulation, gradient_clip_norm)
    return tx, tx.init(params)
