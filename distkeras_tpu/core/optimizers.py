"""Optimizers: Keras-style names/constructors backed by optax.

The reference hands trainers a Keras *worker optimizer* by name or object
(reference: ``distkeras/trainers.py :: Trainer.__init__(..., worker_optimizer)``
compiled per worker in ``workers.py``).  We accept the same spelling —
``'adagrad'``, ``'adam'``, ``'sgd'``, ... or an ``Optimizer`` instance — and
back each with the corresponding optax gradient transformation, which jit/scan
cleanly and shard trivially under SPMD.

BatchNormalization running stats live in the params pytree under a ``"stats"``
key; ``build()`` masks them out of the optimizer update so they are carried,
not trained.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import optax


class Optimizer:
    """Thin named wrapper over an optax transformation factory."""

    def __init__(self, name: str, **hyper):
        self.name = name
        self.hyper = hyper

    def to_optax(self) -> optax.GradientTransformation:
        h = self.hyper
        lr = h.get("learning_rate", _DEFAULT_LR.get(self.name, 0.01))
        if self.name == "sgd":
            return optax.sgd(lr, momentum=h.get("momentum", 0.0),
                             nesterov=h.get("nesterov", False))
        if self.name == "adam":
            return optax.adam(lr, b1=h.get("beta_1", 0.9),
                              b2=h.get("beta_2", 0.999),
                              eps=h.get("epsilon", 1e-7))
        if self.name == "adamw":
            return optax.adamw(lr, b1=h.get("beta_1", 0.9),
                               b2=h.get("beta_2", 0.999),
                               eps=h.get("epsilon", 1e-7),
                               weight_decay=h.get("weight_decay", 1e-4))
        if self.name == "adagrad":
            return optax.adagrad(lr, eps=h.get("epsilon", 1e-7))
        if self.name == "adadelta":
            return optax.adadelta(lr, rho=h.get("rho", 0.95),
                                  eps=h.get("epsilon", 1e-7))
        if self.name == "rmsprop":
            return optax.rmsprop(lr, decay=h.get("rho", 0.9),
                                 eps=h.get("epsilon", 1e-7),
                                 momentum=h.get("momentum", 0.0))
        if self.name == "lamb":
            return optax.lamb(lr)
        raise ValueError(f"Unknown optimizer {self.name!r}")

    def get_config(self):
        return {"name": self.name, **self.hyper}

    def __repr__(self):
        return f"Optimizer({self.name!r}, {self.hyper})"


_DEFAULT_LR = {
    "sgd": 0.01,
    "adam": 0.001,
    "adamw": 0.001,
    "adagrad": 0.01,
    "adadelta": 1.0,
    "rmsprop": 0.001,
    "lamb": 0.001,
}

_ALIASES = {"nadam": "adam", "adamax": "adam"}


def SGD(learning_rate=0.01, momentum=0.0, nesterov=False):
    return Optimizer("sgd", learning_rate=learning_rate, momentum=momentum,
                     nesterov=nesterov)


def Adam(learning_rate=0.001, beta_1=0.9, beta_2=0.999, epsilon=1e-7):
    return Optimizer("adam", learning_rate=learning_rate, beta_1=beta_1,
                     beta_2=beta_2, epsilon=epsilon)


def Adagrad(learning_rate=0.01, epsilon=1e-7):
    return Optimizer("adagrad", learning_rate=learning_rate, epsilon=epsilon)


def Adadelta(learning_rate=1.0, rho=0.95, epsilon=1e-7):
    return Optimizer("adadelta", learning_rate=learning_rate, rho=rho,
                     epsilon=epsilon)


def RMSprop(learning_rate=0.001, rho=0.9, epsilon=1e-7, momentum=0.0):
    return Optimizer("rmsprop", learning_rate=learning_rate, rho=rho,
                     epsilon=epsilon, momentum=momentum)


def get_optimizer(spec: Any, learning_rate: Optional[float] = None) -> Optimizer:
    """Resolve a Keras-style optimizer spec: name string or Optimizer."""
    if isinstance(spec, Optimizer):
        return spec
    if isinstance(spec, str):
        name = _ALIASES.get(spec.lower(), spec.lower())
        hyper = {}
        if learning_rate is not None:
            hyper["learning_rate"] = learning_rate
        return Optimizer(name, **hyper)
    raise TypeError(f"Cannot interpret optimizer spec {spec!r}")


def _trainable_mask(params):
    """Pytree mask: False for BatchNorm running ``stats`` subtrees."""
    def mask_layer(p):
        if isinstance(p, dict):
            return {k: (False if k == "stats"
                        else jax.tree_util.tree_map(lambda _: True, v))
                    for k, v in p.items()}
        return jax.tree_util.tree_map(lambda _: True, p)
    return [mask_layer(p) for p in params]


def build(spec: Any, params, learning_rate: Optional[float] = None):
    """Build (optax_tx, opt_state) for a params pytree, masking non-trainables.

    Returns the transformation and its initialized state.
    """
    opt = get_optimizer(spec, learning_rate)
    tx = optax.masked(opt.to_optax(), _trainable_mask(params))
    return tx, tx.init(params)
