"""Keras → native model conversion.

The reference's public API takes an actual ``keras.Model``
(reference: ``distkeras/trainers.py :: Trainer.__init__(keras_model=...)``;
its own MNIST-ConvNet examples build FUNCTIONAL models, not just
Sequential).  For drop-in familiarity our trainers accept one too: this
adapter converts a Keras ``Sequential`` OR a single-input single-output
linear-chain ``Functional`` model of supported layer types into the native
declarative ``Sequential`` (whose forward pass is a pure jittable
function), and extracts the Keras weights **re-ordered into the native
pytree leaf order** so a converted model starts from identical parameters.
Branching graphs (skip connections, merges, shared layers) are rejected
loudly — converting them to a chain would silently change the function.

Import of ``keras`` is deferred and optional — the framework itself never
needs it; only users handing us Keras objects do.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .model import Sequential
from . import layers as L


def _require_keras():
    try:
        import keras  # noqa: F401
        return keras
    except ImportError as e:  # pragma: no cover - env without keras
        raise ImportError(
            "Converting a Keras model requires the `keras` package; "
            "build the model with distkeras_tpu.core layers instead "
            "(same constructor surface: Dense/Conv2D/MaxPooling2D/...)."
        ) from e


def _act_name(activation) -> str:
    name = getattr(activation, "__name__", None) or str(activation)
    return {"linear": None}.get(name, name)


def _convert_layer(kl) -> List[L.Layer]:
    """One Keras layer → zero or more native layers."""
    t = type(kl).__name__
    cfg = kl.get_config()
    if t == "Dense":
        return [L.Dense(cfg["units"], activation=_act_name(kl.activation),
                        use_bias=cfg.get("use_bias", True))]
    if t == "Conv2D":
        if cfg.get("data_format") == "channels_first":
            raise ValueError("channels_first Conv2D not supported (TPU-native "
                             "layout is NHWC)")
        dil = tuple(np.broadcast_to(cfg.get("dilation_rate", 1), (2,)))
        if dil != (1, 1) or cfg.get("groups", 1) != 1:
            raise ValueError(
                f"Conv2D with dilation_rate={dil} / groups="
                f"{cfg.get('groups', 1)} is not supported by the converter; "
                "converting would silently change the computed function")
        return [L.Conv2D(cfg["filters"], cfg["kernel_size"],
                         strides=cfg.get("strides", 1),
                         padding=cfg.get("padding", "valid"),
                         activation=_act_name(kl.activation),
                         use_bias=cfg.get("use_bias", True))]
    if t == "MaxPooling2D":
        return [L.MaxPooling2D(cfg["pool_size"], cfg.get("strides"),
                               cfg.get("padding", "valid"))]
    if t == "AveragePooling2D":
        return [L.AveragePooling2D(cfg["pool_size"], cfg.get("strides"),
                                   cfg.get("padding", "valid"))]
    if t == "GlobalAveragePooling2D":
        return [L.GlobalAveragePooling2D()]
    if t == "Flatten":
        return [L.Flatten()]
    if t == "Reshape":
        return [L.Reshape(cfg["target_shape"])]
    if t == "Activation":
        return [L.Activation(_act_name(kl.activation))]
    if t == "Dropout":
        return [L.Dropout(cfg["rate"])]
    if t == "BatchNormalization":
        axis = cfg.get("axis", -1)
        axis = axis[0] if isinstance(axis, (list, tuple)) else axis
        if axis not in (-1, 3) or not cfg.get("center", True) \
                or not cfg.get("scale", True):
            raise ValueError(
                "BatchNormalization with axis != last or center/scale=False "
                "is not supported by the converter")
        return [L.BatchNormalization(cfg.get("momentum", 0.99),
                                     cfg.get("epsilon", 1e-3))]
    if t == "Embedding":
        return [L.Embedding(cfg["input_dim"], cfg["output_dim"])]
    if t == "LayerNormalization":
        axis = cfg.get("axis", -1)
        axis = axis[0] if isinstance(axis, (list, tuple)) else axis
        if axis != -1 or not cfg.get("center", True) \
                or not cfg.get("scale", True):
            raise ValueError(
                "LayerNormalization with axis != -1 or center/scale=False "
                "is not supported by the converter")
        return [L.LayerNormalization(cfg.get("epsilon", 1e-3))]
    if t == "InputLayer":
        return []
    raise ValueError(f"Unsupported Keras layer type {t!r}")


def _ordered_layers(km) -> List:
    """Layers in forward (data-flow) order.

    Keras ``Sequential``: ``km.layers`` as listed.  Functional
    ``keras.Model``: the unique input→output chain, recovered from the
    inbound-node graph; anything non-linear — multiple inputs/outputs, a
    layer called twice, a merge (Add/Concatenate), a branch — is rejected
    with a specific message rather than silently mis-converted.
    """
    keras = _require_keras()
    if isinstance(km, keras.Sequential):
        return list(km.layers)
    if not isinstance(km, keras.Model):
        raise TypeError(f"expected a keras.Model, got {type(km)!r}")
    inputs = getattr(km, "inputs", None) or []
    outputs = getattr(km, "outputs", None) or []
    if len(inputs) != 1 or len(outputs) != 1:
        raise ValueError(
            f"only single-input single-output Keras models convert "
            f"(got {len(inputs)} inputs, {len(outputs)} outputs)")
    parents = {}
    for kl in km.layers:
        nodes = getattr(kl, "_inbound_nodes", [])
        if len(nodes) != 1:
            raise ValueError(
                f"Keras layer {kl.name!r} is called {len(nodes)} times — "
                "shared layers are not linear-chain convertible")
        ps = [t._keras_history[0].name for t in nodes[0].input_tensors]
        if len(ps) > 1:
            raise ValueError(
                f"Keras layer {kl.name!r} merges {len(ps)} inputs — "
                "skip connections/merges are not linear-chain convertible")
        parents[kl.name] = ps
    child = {}
    for name, ps in parents.items():
        for p in ps:
            if p in child:
                raise ValueError(
                    f"Keras layer {p!r} feeds both {child[p]!r} and "
                    f"{name!r} — branching graphs are not linear-chain "
                    "convertible")
            child[p] = name
    by_name = {kl.name: kl for kl in km.layers}
    roots = [kl for kl in km.layers if not parents[kl.name]]
    if len(roots) != 1:
        raise ValueError(f"expected one root (InputLayer), found "
                         f"{[r.name for r in roots]}")
    chain = [roots[0]]
    while chain[-1].name in child:
        chain.append(by_name[child[chain[-1].name]])
    if len(chain) != len(km.layers):
        missing = sorted(set(by_name) - {kl.name for kl in chain})
        raise ValueError(f"layers {missing} are not on the input→output "
                         "chain — not a linear model")
    out_name = outputs[0]._keras_history[0].name
    if chain[-1].name != out_name:
        raise ValueError(f"chain ends at {chain[-1].name!r} but the model "
                         f"output comes from {out_name!r}")
    return chain


def convert_keras_model(km) -> Sequential:
    """Convert a Keras Sequential or linear-chain functional model to the
    native spec (no weights)."""
    _require_keras()
    in_shape = getattr(km, "input_shape", None)
    if in_shape is None:
        raise ValueError("Keras model must be built (call it once or pass "
                         "input_shape) before conversion")
    native_layers: List[L.Layer] = []
    for kl in _ordered_layers(km):
        native_layers.extend(_convert_layer(kl))
    return Sequential(native_layers, input_shape=tuple(in_shape[1:]),
                      name=getattr(km, "name", "converted"))


def keras_weights(km) -> List[np.ndarray]:
    """Keras weights re-ordered to the native pytree leaf order.

    Native leaves per layer are dict keys in sorted order
    (Dense: bias, kernel; BatchNorm: offset, scale, stats.mean, stats.var),
    while Keras ``get_weights`` returns [kernel, bias] / [gamma, beta,
    moving_mean, moving_var].  Iterates the same forward order as
    ``convert_keras_model`` (chain order for functional models).
    """
    _require_keras()
    out: List[np.ndarray] = []
    for kl in _ordered_layers(km):
        t = type(kl).__name__
        w = [np.asarray(a) for a in kl.get_weights()]
        if t in ("Dense", "Conv2D"):
            if len(w) == 2:       # [kernel, bias] → bias, kernel
                out.extend([w[1], w[0]])
            else:                 # no bias → kernel only
                out.extend(w)
        elif t == "BatchNormalization":
            if len(w) != 4:
                raise ValueError(
                    f"BatchNormalization layer {kl.name!r} has {len(w)} "
                    "weight arrays (expected 4: gamma, beta, moving_mean, "
                    "moving_var) — center=False/scale=False are unsupported")
            gamma, beta, mean, var = w
            out.extend([beta, gamma, mean, var])
        elif t == "Embedding":
            out.extend(w)
        elif t == "LayerNormalization":
            if len(w) != 2:
                raise ValueError(
                    f"LayerNormalization layer {kl.name!r} has {len(w)} "
                    "weight arrays (expected 2: gamma, beta)")
            gamma, beta = w
            out.extend([beta, gamma])  # native sorted order: offset, scale
        elif w:
            raise ValueError(f"Unexpected weights on Keras layer {t!r}")
    return out
