"""Utility helpers (API parity with reference ``distkeras/utils.py``).

The reference's utils are Keras/Spark glue: model (de)serialization, one-hot
vectors, DataFrame row construction, shuffling, uniform weight init.  The
same-named functions here operate on the native Sequential/Dataset types.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

from .core.model import (Sequential, FittedModel, serialize_model,
                         deserialize_model)
from .data.dataset import Dataset


# -- platform selection -------------------------------------------------------

def honor_platform_env() -> None:
    """Apply ``JAX_PLATFORMS=cpu`` / ``--xla_force_host_platform_device_count``
    through the jax config API.

    Needed because jax may be imported at interpreter startup (sitecustomize)
    with the sandbox's platform snapshot, in which case the env vars alone are
    ignored and the first ``jax.devices()`` call silently binds the default
    platform.  Call this at the top of any script that should honor the env
    (the examples and tests do); it is a no-op once a backend is live.
    """
    import os
    import re

    if "cpu" not in os.environ.get("JAX_PLATFORMS", ""):
        return
    m = re.search(r"host_platform_device_count=(\d+)",
                  os.environ.get("XLA_FLAGS", ""))
    try:
        jax.config.update("jax_platforms", "cpu")
        if m:
            jax.config.update("jax_num_cpu_devices", int(m.group(1)))
    except (RuntimeError, AttributeError):
        pass  # backend already initialized (or old jax); keep what it has


# -- model (de)serialization (reference: serialize_keras_model) --------------

def serialize_keras_model(model) -> dict:
    """Serialize a FittedModel — or an actual ``keras.Model`` via the adapter
    (reference: ``utils.py :: serialize_keras_model`` pickles json+weights)."""
    if isinstance(model, FittedModel):
        return model.serialize()
    from .core.keras_adapter import convert_keras_model, keras_weights
    native = convert_keras_model(model)
    params = native.init(jax.random.PRNGKey(0), native.input_shape)
    params = native.set_weights(params, keras_weights(model))
    return serialize_model(native, params)


def deserialize_keras_model(blob: dict) -> FittedModel:
    model, params = deserialize_model(blob)
    return FittedModel(model, params)


# -- vector/row helpers -------------------------------------------------------

def to_dense_vector(value: float, n_dim: int) -> np.ndarray:
    """One-hot vector with ``value`` as the hot index (reference:
    ``utils.py :: to_dense_vector`` backing OneHotTransformer)."""
    out = np.zeros((n_dim,), np.float32)
    out[int(value)] = 1.0
    return out


def new_dataframe_row(row: dict, name: str, value) -> dict:
    """Append a column to a row dict (reference: ``utils.new_dataframe_row``
    rebuilds a Spark Row with an extra field)."""
    out = dict(row)
    out[name] = value
    return out


def shuffle(dataset: Dataset, seed: Optional[int] = None) -> Dataset:
    """Global shuffle (reference: ``utils.shuffle(df)``)."""
    return dataset.shuffle(seed)


def precache(dataset: Dataset) -> Dataset:
    """Parity stub for ``df.cache()`` — our datasets are already host-resident
    numpy; returns the dataset unchanged."""
    return dataset


def uniform_weights(fitted: FittedModel, constraints: Sequence[float] = (-0.5, 0.5),
                    seed: int = 0) -> FittedModel:
    """Re-init all weights uniformly in [lo, hi] (reference:
    ``utils.uniform_weights``)."""
    lo, hi = constraints
    rng = np.random.default_rng(seed)
    new = [rng.uniform(lo, hi, size=w.shape).astype(w.dtype)
           for w in fitted.get_weights()]
    return FittedModel(fitted.model,
                       fitted.model.set_weights(fitted.params, new))


def history_average(history: Sequence[float]) -> float:
    return float(np.mean(np.asarray(history))) if len(history) else 0.0


def history_executors_average(histories) -> float:
    """Average final loss across worker histories (reference keeps per-worker
    loss lists; ours are already merged per-round means)."""
    return history_average([h[-1] if isinstance(h, (list, np.ndarray)) else h
                            for h in histories])
