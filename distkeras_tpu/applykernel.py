"""Apply-kernel resolution for the host-PS core (``apply_kernel=`` knob).

The PS apply path reduces to two primitives — a dense in-place axpy
(``center += scale * delta``) and a sequential scatter-add
(``np.add.at(flat, indices, values)``, the sparse-commit and coalesced-drain
workhorse).  ``csrc/applykernel.cpp`` provides native twins of both with
**bit-identical** results (same rounding count, same accumulation order; the
extension is compiled with ``-ffp-contract=off`` so no FMA collapses numpy's
two roundings into one).

Same build/fallback pattern as the wire codec: the extension is optional,
the pure-NumPy path is the default AND the reference — ``apply_kernel=None``
(or ``"numpy"``) never touches the native module, ``"native"`` requires it
(loud error when unbuilt), ``"auto"`` uses it when importable and falls back
silently (the bench-friendly setting).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    from . import _applykernel as _native
except ImportError:  # pragma: no cover - depends on build environment
    _native = None

#: the accepted ``apply_kernel=`` spellings
KERNEL_CHOICES = (None, "numpy", "native", "auto")


def have_native() -> bool:
    return _native is not None


def resolve(name: Optional[str]):
    """Resolve an ``apply_kernel=`` knob value to the native module or None.

    None / ``"numpy"`` → None (the pure-NumPy reference path);
    ``"native"`` → the built extension, raising if it is absent;
    ``"auto"`` → the extension when built, None otherwise.
    """
    if name in (None, "numpy"):
        return None
    if name == "auto":
        return _native
    if name == "native":
        if _native is None:
            raise RuntimeError(
                "apply_kernel='native' but distkeras_tpu._applykernel is not "
                "built — run `python setup.py build_ext --inplace` (or use "
                "apply_kernel='auto' to fall back to numpy silently)")
        return _native
    raise ValueError(
        f"apply_kernel must be one of {KERNEL_CHOICES}, got {name!r}")


def axpy(kernel, dst: np.ndarray, src: np.ndarray, scale: float) -> None:
    """``dst += scale * src`` over flat f32 arrays, through ``kernel`` when
    given (bit-equal either way).  ``dst`` must be a writable f32 view."""
    if kernel is not None:
        kernel.axpy_f32(dst, np.ascontiguousarray(src, np.float32), scale)
    elif scale == 1.0:
        dst += src
    else:
        dst += scale * src


def scatter_add(kernel, dst: np.ndarray, idx: np.ndarray,
                vals: np.ndarray) -> None:
    """``dst[idx[i]] += vals[i]`` in array order (``np.add.at`` semantics),
    through ``kernel`` when given.  ``idx`` int64, ``vals``/``dst`` f32."""
    if kernel is not None:
        kernel.scatter_add_f32(dst, np.ascontiguousarray(idx, np.int64),
                               np.ascontiguousarray(vals, np.float32))
    else:
        np.add.at(dst, idx, vals)


def row_scatter_add(kernel, dst2d: np.ndarray, rows: np.ndarray,
                    vals2d: np.ndarray, scale: float = 1.0) -> None:
    """``dst2d[rows[i]] += scale * vals2d[i]`` row by row, in array order —
    the row-sparse embedding apply (``networking.RowSparseDelta``).  Each
    touched row is one contiguous ``axpy``, so the native and NumPy paths
    share per-row arithmetic and stay bit-identical; duplicated rows (never
    emitted by the wire contract, tolerated for direct callers) accumulate
    sequentially, the ``np.add.at`` semantics."""
    for i, r in enumerate(rows):
        axpy(kernel, dst2d[int(r)], vals2d[i], scale)
