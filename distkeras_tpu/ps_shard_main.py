"""Standalone PS shard process: one shard of a sharded host-PS as its own
OS process (``python -m distkeras_tpu.ps_shard_main <config.json> [shard]``).

The in-process topology wraps every shard in a ``ShardedServerGroup``
inside the driver; this entrypoint is the cross-process twin — the driver
(or any ``JobRunner`` host) launches one of these per shard and workers
dial them exactly like in-process shards, because the process boundary is
invisible to the wire protocol.  Three contracts make the shard
*survivable* rather than merely remote:

- **Journal-backed respawn.**  A ``journal_dir`` (shared scratch: NFS in a
  real deployment, a tempdir under ``LocalJobRunner``) holds this shard's
  ``ShardJournal``.  On start the newest snapshot — if any — restores the
  center slice and clock, and the server comes up with its **generation
  bumped**, so commits computed against the pre-crash center are rejected
  by the existing generation handshake.  Windows committed after the last
  snapshot are dropped: the same bounded-loss contract as the in-process
  ``ShardSupervisor.respawn_shard``, now crossing an OS process death.
- **Same-address respawn.**  The first launch binds an ephemeral port and
  publishes ``host port generation`` to ``addr_dir/shard_<j>.addr``
  (atomic rename); a respawn finds the file and re-binds the *same* port,
  so workers' recovery redial loops reconnect without a membership change.
- **Clean handoff.**  SIGTERM/SIGINT journal a final snapshot and stop the
  server; the driver gathers the final center over the wire (a plain
  sharded pull) before terminating the group.

Config JSON keys: ``algorithm``, ``model_path``, ``num_workers``,
``num_shards`` (for the deterministic ``make_shard_plan``), ``bind_host``,
``addr_dir``, ``journal_dir`` (optional — no journal means no restore),
``ps_core``, ``coalesce``, ``apply_kernel``, ``snapshot_interval`` (s).
The shard id comes from argv (preferred) or ``DISTKERAS_TPU_PROCESS_ID``
(the ``Job.host_env`` slot), so the same config file serves every shard.
"""

import json
import os
import signal
import sys
import threading
import time

import numpy as np


def _publish_addr(path: str, host: str, port: int, generation: int) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(f"{host} {port} {generation}\n")
    os.replace(tmp, path)


def read_addr(path: str):
    """Parse a published ``shard_<j>.addr`` file → (host, port, generation)."""
    with open(path) as f:
        host, port, gen = f.read().split()
    return host, int(port), int(gen)


def main(argv=None) -> int:
    argv = sys.argv if argv is None else argv
    if len(argv) not in (2, 3):
        print("usage: python -m distkeras_tpu.ps_shard_main <config.json> "
              "[shard_id]", file=sys.stderr)
        return 2
    from .utils import honor_platform_env
    honor_platform_env()

    with open(argv[1]) as f:
        cfg = json.load(f)
    if len(argv) == 3:
        shard_id = int(argv[2])
    else:
        shard_id = int(os.environ.get("DISTKERAS_TPU_PROCESS_ID",
                                      cfg.get("shard_id", 0)))

    from .parameter_servers import (allocate_parameter_server,
                                    make_socket_server)
    from .ps_sharding import make_shard_plan
    from .ps_worker_main import load_model_blob
    from .resilience import ShardJournal

    blob = load_model_blob(cfg["model_path"])
    weights = [np.asarray(w) for w in blob["weights"]]
    plan = make_shard_plan([w.shape for w in weights],
                           [w.dtype for w in weights],
                           int(cfg["num_shards"]))
    shard_w = plan.scatter(weights)[shard_id]

    # journal restore (respawn path): newest snapshot wins, generation bumps
    journal = None
    snap_id, clock, generation = 0, 0, 0
    if cfg.get("journal_dir"):
        journal = ShardJournal(cfg["journal_dir"],
                               max_to_keep=int(cfg.get("snap_retention", 2)))
        latest = journal.latest(shard_id)
        if latest is not None:
            shard_w = latest["center"]
            clock = latest["clock"]
            generation = latest["generation"] + 1
            snap_id = latest["snap_id"] + 1

    ps = allocate_parameter_server(
        cfg["algorithm"], {"model": blob["model"], "weights": shard_w},
        int(cfg["num_workers"]), apply_kernel=cfg.get("apply_kernel"))
    ps.num_updates = clock

    # same-address respawn: a published addr file pins this shard's port
    bind_host = cfg.get("bind_host", "127.0.0.1")
    addr_path = os.path.join(cfg["addr_dir"], f"shard_{shard_id}.addr")
    port = 0
    if os.path.exists(addr_path):
        _, port, _ = read_addr(addr_path)

    server = None
    for attempt in range(40):  # the dying predecessor may still hold the port
        try:
            server = make_socket_server(
                ps, host=bind_host, port=port, generation=generation,
                ps_core=cfg.get("ps_core", "event"),
                coalesce=bool(cfg.get("coalesce", True)),
                idle_deadline=cfg.get("idle_deadline"))
            server.start()
            break
        except OSError:
            if attempt == 39:
                raise
            time.sleep(0.25)
    _publish_addr(addr_path, bind_host, server.port, generation)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    def snapshot_once() -> None:
        nonlocal snap_id
        if journal is None:
            return
        with server.ps._lock:
            center = [w.copy() for w in server.ps.center]
            clk = server.ps.num_updates
        journal.save(shard_id, snap_id, center, clk, generation)
        snap_id += 1

    interval = float(cfg.get("snapshot_interval", 0.5))
    if journal is not None:
        def journal_loop() -> None:
            while not stop.wait(interval):
                snapshot_once()
        threading.Thread(target=journal_loop, daemon=True,
                         name="dkt-shard-journal").start()

    stop.wait()
    snapshot_once()  # the clean-shutdown snapshot: zero-loss handoff
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
