"""Rotary position embeddings (RoPE, Su et al. 2021).

No reference counterpart (SURVEY.md §2.3: the reference has no sequence
models) — part of the long-context layer.  Each (even, odd) channel pair of
q and k is rotated by an angle proportional to the token's absolute
position; dot products between rotated q and k then depend only on the
RELATIVE distance, which is what makes RoPE extrapolate and window/cache
naturally.  Rotation happens at projection time, before the attention
dispatch, so it composes with every impl (XLA, flash, ring) and with
GQA/sliding-window unchanged.

Arithmetic is f32 (bf16-safe angles), output in the input dtype.
"""

from __future__ import annotations

import jax.numpy as jnp


def validate_rope_dim(dim: int) -> int:
    """The single RoPE head-dim rule, shared by the layer constructors
    (eager) and the op itself (trace time): channel pairs need an even
    dim."""
    if int(dim) % 2:
        raise ValueError(f"RoPE needs an even head dim, got {dim}")
    return int(dim)


def rope_angles(positions, dim: int, theta: float = 10000.0,
                scale: float = 1.0):
    """(S,) integer positions → (S, dim/2) rotation angles.

    ``scale`` > 1 is LINEAR position-interpolation context extension
    (Chen et al. 2023): positions are divided by ``scale``, squeezing a
    ``scale``× longer context into the rotation range the model trained
    on.  For the NTK-aware variant keep ``scale`` at 1 and raise ``theta``
    via ``ntk_theta``."""
    validate_rope_dim(dim)
    freqs = theta ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    pos = positions.astype(jnp.float32) / scale
    return pos[:, None] * freqs[None, :]


def ntk_theta(factor: float, dim: int, theta: float = 10000.0) -> float:
    """NTK-aware context extension: the base-``theta`` adjustment
    ``theta · factor^(dim / (dim - 2))`` that stretches the low-frequency
    channels by ~``factor`` while leaving the high-frequency (local
    order) channels nearly untouched — extends context without the
    high-frequency aliasing plain linear interpolation causes.  Pass the
    result as ``rope_theta`` (training-free extension by ~``factor``×)."""
    validate_rope_dim(dim)
    if dim <= 2:
        raise ValueError(f"ntk_theta needs head dim > 2 (the exponent is "
                         f"dim/(dim-2)), got {dim}")
    if factor < 1.0:
        raise ValueError(f"extension factor must be >= 1, got {factor}")
    return float(theta * factor ** (dim / (dim - 2)))


def apply_rope(x, positions, theta: float = 10000.0, scale: float = 1.0):
    """Rotate (B, S, H, D) q or k by per-position angles.

    ``positions``: (S,) absolute token positions — pass the true offsets
    when decoding a suffix against a cache — or (B, S) PER-ROW positions
    (a batched decode step where every row sits at its own position; the
    serving engine's slot pool).  ``theta``/``scale``: see ``rope_angles``
    (context-extension knobs; defaults = classic RoPE).
    """
    b, s, h, d = x.shape
    if getattr(positions, "ndim", 1) == 2:            # (B, S) per-row
        validate_rope_dim(d)
        freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
        pos = positions.astype(jnp.float32) / scale
        ang = pos[..., None] * freqs[None, None, :]   # (B, S, d/2)
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
        x32 = x.astype(jnp.float32)
        x1, x2 = x32[..., 0::2], x32[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin,
                         x1 * sin + x2 * cos], axis=-1).reshape(b, s, h, d)
        return out.astype(x.dtype)
    ang = rope_angles(positions, d, theta, scale)     # (S, d/2)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., 0::2], x32[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin,
                     x1 * sin + x2 * cos], axis=-1).reshape(b, s, h, d)
    return out.astype(x.dtype)


def validate_rope_scaling(theta: float, scale: float):
    """The single rope_theta/rope_scale rule, shared by every constructor
    that exposes the context-extension knobs."""
    if theta <= 0.0:
        # theta**(-2i/d) is undefined/NaN for theta <= 0 and would only
        # surface as silent NaNs at the first forward
        raise ValueError(f"rope_theta must be > 0, got {theta}")
    if scale < 1.0:
        raise ValueError(f"rope_scale must be >= 1, got {scale}")
    return float(theta), float(scale)
