"""Fused flash-attention forward AND backward kernels in Pallas (TPU).

The hot op of the long-context path.  XLA's unfused attention materializes
the (S×S) score matrix in HBM; these kernels stream k/v blocks through VMEM
with the online-softmax recurrence, so HBM traffic stays O(S·D) per head and
VMEM residency stays O(block²) — the standard flash schedule, shaped for the
MXU:

 - every kernel runs on a 3-D grid (batch·heads, outer block, inner block):
   the *inner* grid dimension streams the contraction blocks, with f32 VMEM
   scratch accumulators carried across inner iterations and the output block
   written on the last one (TPU grids execute sequentially, innermost
   fastest, and an output block whose index map ignores the inner dim stays
   resident in VMEM) — so no kernel ever holds a whole (S, D) operand in
   VMEM, which is what bounds sequence length;
 - forward, grid (B·H, S/block_q, S/block_k): online-softmax over k/v
   blocks; alongside the output it writes the per-row logsumexp — the O(S)
   statistics the backward needs;
 - backward is the classic two-pass recompute schedule over the saved
   (q, k, v, o, lse) — no (S×S) intermediate is ever materialized:
     * dq kernel, grid (B·H, S/block_q, S/block_k): recompute
       p = exp(q·kᵀ·scale − lse), accumulate dq += (p ∘ (dO·vᵀ − Δ))·k·scale
       with Δ = rowsum(dO ∘ O) computed in-VMEM from the resident blocks;
     * dk/dv kernel, grid (B·H, S/block_k, S/block_q): accumulate
       dv += pᵀ·dO and dk += (p ∘ (dO·vᵀ − Δ))ᵀ·q·scale;
   causal inner blocks that are fully masked skip their compute via
   ``pl.when`` (the standard ~2x causal saving);
 - scores/accumulators are f32 tiles — MXU matmuls with f32 accumulation,
   2-D shapes throughout (TPU vector layout); per-row statistics are stored
   broadcast over a 128-lane trailing dim (the TPU-tileable layout for
   per-row stats, same trick as jax's reference TPU flash kernel).

On non-TPU backends the kernels run in Pallas interpret mode (tests); the
``ops.attention.attention`` dispatcher only routes here on TPU.  The XLA
reference (``ops.attention.dot_product_attention``) stays the correctness
oracle — gradient parity is asserted in tests/test_flash_attention.py, and
``tests/test_tpu_smoke.py`` checks the compiled kernels on real hardware.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._vma import out_struct
from .attention import validate_window

NEG_INF = float("-inf")
_LANES = 128  # TPU lane width: per-row stats are stored broadcast over it


def _causal_mask(s, q0, k0, bq, bk, window=None):
    q_pos = q0 + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    k_pos = k0 + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
    hide = k_pos > q_pos
    if window is not None:  # sliding window: q sees (q_pos-window, q_pos]
        hide = hide | (k_pos <= q_pos - window)
    return jnp.where(hide, NEG_INF, s)


def _live_kq(qi, kj, bq, bk, causal, window):
    """Is k-block kj within reach of q-block qi?  Causal skips the future;
    a sliding window additionally skips blocks entirely behind the window —
    that drops compute to O(S·W) per head instead of the full causal
    triangle."""
    live = (kj * bk < (qi + 1) * bq) if causal else True
    if window is not None:
        live = live & ((kj + 1) * bk + window > qi * bq + 1)
    return live


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  num_k: int, window: Optional[int] = None):
    # outputs/scratch: [lse_ref,] m_scr, l_scr, acc_scr — the lse output only
    # exists on the training path (save_residuals); inference pays nothing
    lse_ref = rest[0] if len(rest) == 4 else None
    m_scr, l_scr, acc_scr = rest[-3:]
    qi, kj = pl.program_id(1), pl.program_id(2)
    bq, bk = block_q, block_k

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: blocks entirely in the future of this q block contribute
    # nothing — skip their compute (the standard flash causal saving);
    # a window also skips blocks entirely behind it
    live = _live_kq(qi, kj, bq, bk, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi * bq, kj * bk, bq, bk, window)
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        safe = jnp.where(new_m == NEG_INF, 0.0, new_m)
        p = jnp.exp(s - safe)                             # (bq, bk)
        corr = jnp.exp(m - safe)                          # (bq, 1)
        acc_scr[...] = acc_scr[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[...] = jnp.broadcast_to(new_m, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l, l_scr.shape)

    @pl.when(kj == num_k - 1)
    def _finalize():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp of the scaled scores per row: p = exp(s - lse) in
            # the backward.  Fully-masked rows keep a finite lse (their p
            # is 0 wherever s = -inf).
            safe_m = jnp.where(m == NEG_INF, 0.0, m)
            lse_ref[0] = jnp.broadcast_to(safe_m + jnp.log(l),
                                          lse_ref.shape[1:])


def _flash_forward(q, k, v, scale: float, causal: bool, block_q: int,
                   block_k: int, interpret: bool,
                   save_residuals: bool = True,
                   window: Optional[int] = None):
    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(f"seq_len {s} not divisible by blocks ({bq},{bk})")
    # (B, S, H, D) → (B·H, S, D): one grid row per (batch, head)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf = fold(q), fold(k), fold(v)

    # out_struct: under shard_map (tp/ulysses paths on TPU) pallas outputs
    # must declare the mesh axes they vary over — they vary as q does
    out_shape = [out_struct(qf.shape, q.dtype, qf)]
    out_specs = [pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0))]
    if save_residuals:  # inference skips the O(128·S) lse write entirely
        out_shape.append(
            out_struct((b * h, s, _LANES), jnp.float32, qf))
        out_specs.append(
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, kj: (bh, qi, 0)))

    res = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_k=s // bk,
                          window=window),
        out_shape=tuple(out_shape),
        grid=(b * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (bh, kj, 0)),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf)
    out = res[0]
    lse = res[1] if save_residuals else None
    unfold = lambda t: t.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return unfold(out), lse


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_scr,
               *, scale: float, causal: bool, block_q: int, block_k: int,
               num_k: int, window: Optional[int] = None):
    qi, kj = pl.program_id(1), pl.program_id(2)
    bq, bk = block_q, block_k

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    live = _live_kq(qi, kj, bq, bk, causal, window)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)                  # (bq, d)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]                          # (bq, 1)
        # Δ = rowsum(dO ∘ O), computed in-VMEM from the resident blocks
        delta = jnp.sum(do * o, axis=-1, keepdims=True)   # (bq, 1)
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * bq, kj * bk, bq, bk, window)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                     # (bq, bk)
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
                dk_scr, dv_scr, *, scale: float, causal: bool, block_q: int,
                block_k: int, num_q: int, window: Optional[int] = None):
    ki, qi = pl.program_id(1), pl.program_id(2)
    bq, bk = block_q, block_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # causal: q blocks entirely before this k block see none of it; a
    # window also skips q blocks entirely past this k block's reach
    live = _live_kq(qi, ki, bq, bk, causal, window)

    @pl.when(live)
    def _step():
        k = k_ref[0].astype(jnp.float32)                  # (bk, d)
        v = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)                  # (bq, d)
        do = do_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, 0:1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi * bq, ki * bk, bq, bk, window)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # pᵀ·dO (bk, d)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                     # (bq, bk)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # dsᵀ·q (bk, d)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, scale: float, causal: bool,
                    block_q: int, block_k: int, interpret: bool,
                    window: Optional[int] = None):
    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf, of, gf = fold(q), fold(k), fold(v), fold(out), fold(g)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_k=s // bk,
                          window=window),
        out_shape=out_struct(qf.shape, q.dtype, qf),
        grid=(b * h, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (bh, kj, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, qi, kj: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, num_q=s // bq,
                          window=window),
        out_shape=(out_struct(kf.shape, k.dtype, kf),
                   out_struct(vf.shape, v.dtype, vf)),
        grid=(b * h, s // bk, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, _LANES), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0))),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, of, gf, lse)

    unfold = lambda t: t.reshape(b, h, s, d).transpose(0, 2, 1, 3)
    return unfold(dq), unfold(dk), unfold(dv)


def _resolve(q, scale, interpret):
    """nondiff_argnums hand each custom_vjp entry point the raw argument
    values, so defaults resolve in one place for primal/fwd/bwd alike."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return scale, interpret


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None,
                    window: Optional[int] = None):
    """Flash attention on (B, S, H, Dh) tensors; same contract as
    ``ops.attention.dot_product_attention``, including sliding-window
    (``window``, requires causal) — out-of-window k blocks are skipped
    entirely, so windowed compute is O(S·W) per head."""
    window = validate_window(window, causal)
    scale, interpret = _resolve(q, scale, interpret)
    out, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                            interpret, save_residuals=False, window=window)
    return out


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret, window):
    window = validate_window(window, causal)
    scale, interpret = _resolve(q, scale, interpret)
    out, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                              interpret, window=window)
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, block_q, block_k, interpret, window, res, g):
    q, k, v, out, lse = res
    scale, interpret = _resolve(q, scale, interpret)
    return _flash_backward(q, k, v, out, lse, g, scale, causal,
                           block_q, block_k, interpret, window=window)


flash_attention.defvjp(_fwd, _bwd)
