"""Fused flash-attention forward kernel in Pallas (TPU).

The hot op of the long-context path.  XLA's unfused attention materializes
the (S×S) score matrix in HBM; this kernel streams k/v blocks through VMEM
with the online-softmax recurrence, so HBM traffic stays O(S·D) per head —
the standard flash schedule, shaped for the MXU:

 - grid = (batch·heads, S/block_q): one program instance owns one q block,
   resident in VMEM; k/v for its (batch, head) stream in via ``pl.ds`` slices;
 - scores/accumulators are (block_q, block_k)/(block_q, D) f32 tiles — MXU
   matmuls with f32 accumulation, 2-D shapes throughout (TPU vector layout);
 - the running max/denominator are (block_q, 1) columns, not 1-D vectors.

Backward: ``jax.custom_vjp`` recomputes through the XLA reference attention
(``ops.attention.dot_product_attention``) — flash-forward + recompute-backward
is the classic memory/time trade; a fused backward kernel can slot in later
without touching callers.

On non-TPU backends the kernel runs in Pallas interpret mode (tests); the
``ops.attention.attention`` dispatcher only routes here on TPU.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float("-inf")


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
                  block_k: int, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale            # (bq, d)
    bq, d = q.shape
    nk = seq_len // block_k

    m = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    if causal:
        # skip blocks entirely in the future of this q block — the standard
        # flash schedule halves causal FLOPs
        nk = jnp.minimum(nk, ((qi + 1) * bq + block_k - 1) // block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        safe = jnp.where(new_m == NEG_INF, 0.0, new_m)
        p = jnp.exp(s - safe)                            # (bq, bk)
        corr = jnp.exp(m - safe)                         # (bq, 1)
        acc = acc * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        return new_m, l, acc

    m, l, acc = jax.lax.fori_loop(0, nk, body, (m, l, acc))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, scale: float, causal: bool, block_q: int,
                   block_k: int, interpret: bool):
    b, s, h, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    if s % bq or s % bk:
        raise ValueError(f"seq_len {s} not divisible by blocks ({bq},{bk})")
    # (B, S, H, D) → (B·H, S, D): one grid row per (batch, head)
    fold = lambda t: t.transpose(0, 2, 1, 3).reshape(b * h, s, d)
    qf, kf, vf = fold(q), fold(k), fold(v)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_len=s),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(b * h, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 128,
                    block_k: int = 128, interpret: Optional[bool] = None):
    """Flash attention on (B, S, H, Dh) tensors; same contract as
    ``ops.attention.dot_product_attention``."""
    scale = (1.0 / math.sqrt(q.shape[-1])) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret)


def _fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    from .attention import dot_product_attention
    q, k, v = res
    _, vjp = jax.vjp(
        lambda a, b, c: dot_product_attention(a, b, c, causal=causal,
                                              scale=scale), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
