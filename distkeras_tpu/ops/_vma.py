"""Varying-mesh-axes (vma) plumbing for Pallas kernels under shard_map.

jax's shard_map tracks, per value, the set of mesh axes it varies over and
refuses ops that mix mismatched sets (``check_vma``).  Two places in a
Pallas kernel need explicit plumbing when the kernel is traced inside a
shard_map region (compiled TPU kernels trace in a fresh context and never
see vma; *interpret mode* — the CPU test path — inlines the kernel body
into the traced program, so its ops do):

 - ``out_struct``: pallas_call output avals must declare their vma (a
   kernel output varies exactly as its inputs do);
 - ``match_vma``: kernel-internal constants (iota position grids, masks)
   are unvarying and must be ``pvary``'d before meeting varying refs.

Both are no-ops outside shard_map and in compiled kernels.
"""

from __future__ import annotations

import jax

_EMPTY = frozenset()

# jax < 0.6 has neither ``jax.typeof`` nor vma tracking in shard_map
# (check_vma arrived with the vma-typed shard_map) — there is nothing to
# plumb, so every value reads as unvarying and both helpers no-op.
_typeof = getattr(jax, "typeof", None)


def _vma_of(x):
    if _typeof is None:
        return _EMPTY
    return getattr(_typeof(x), "vma", None) or _EMPTY


def out_struct(shape, dtype, like):
    """ShapeDtypeStruct for a pallas output varying as ``like`` does."""
    vma = _vma_of(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def match_vma(x, like):
    """Lift ``x`` (typically an iota/mask built in-kernel) to ``like``'s
    varying axes so elementwise ops between them type-check."""
    missing = tuple(a for a in _vma_of(like) if a not in _vma_of(x))
    return jax.lax.pvary(x, missing) if missing else x
