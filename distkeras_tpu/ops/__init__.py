# Pallas/XLA custom ops live here (populated as profiling identifies
# fusion gaps; the v1 compute path is pure XLA which already fuses the
# reference workloads' Dense/Conv+activation chains).
