"""Attention ops — XLA reference implementation + dispatch.

No counterpart exists in the reference (its models are MLPs/small ConvNets;
SURVEY.md §2.3 "sequence parallelism: absent") — this is part of the
framework's long-context layer.  Layout is **BSHD** ``(batch, seq, heads,
head_dim)`` throughout: S in the second dimension keeps the (S, Dh) matmuls
MXU-shaped and makes the sequence axis shardable for ring attention
(``parallel/ring.py``).

``impl``: ``"xla"`` — plain jnp, XLA fuses the softmax chain; ``"pallas"`` —
the fused flash kernel in ``ops/flash_attention.py`` (TPU); ``None`` — pick
pallas on TPU when shapes qualify, else xla.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def validate_window(window: Optional[int], causal: bool) -> Optional[int]:
    """The single sliding-window rule, shared by every attention entry
    point (XLA, flash, ring, layers): requires causal, must be >= 1."""
    if window is None:
        return None
    if not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if int(window) < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return int(window)


def dot_product_attention(q, k, v, *, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset=None, kv_length=None,
                          window: Optional[int] = None,
                          kv_positions=None, segment_ids=None):
    """Softmax(q·kᵀ)·v with f32 softmax arithmetic.

    q: (B, Sq, H, Dh); k, v: (B, Sk, Hkv, Dh) → (B, Sq, H, Dh), in q.dtype.
    Hkv may divide H (grouped-query / multi-query attention): each group of
    H/Hkv query heads shares one k/v head, shrinking the KV projection and —
    at decode time — the KV cache by the same factor.  Hkv == H is classic
    MHA; the grouped einsum below reduces to it at G == 1.

    ``window`` (requires ``causal``): sliding-window attention — query at
    position p sees keys in (p - window, p], i.e. itself and the previous
    ``window - 1`` tokens.  Information still propagates ``window`` tokens
    per layer, so reach grows with depth.  Here (the XLA path) the window
    is mask-only — scores are computed then hidden; the flash kernel
    (``flash_attention(window=...)``, used automatically on TPU) skips
    out-of-window blocks outright for true O(S·W) compute.

    KV-cache decoding hooks (``core/decode.py`` — keeps decode on this
    exact numerics path): ``q_offset`` places query i at absolute position
    ``q_offset + i`` for the causal mask (queries continuing a cached
    prefix); ``kv_length`` masks key slots >= it out of the softmax
    (zero-filled tail of a preallocated cache); ``kv_positions`` gives
    each key slot an EXPLICIT absolute position (rolling/ring-buffer
    caches, where slot order ≠ position order — negative = empty slot),
    overriding the identity slot→position layout that ``causal``/
    ``kv_length`` otherwise assume.  All accept tracers.

    Each hook also accepts a PER-ROW form — ``q_offset``/``kv_length`` of
    shape (B,), ``kv_positions`` of shape (B, Sk) — so one batched decode
    step can advance every row at its own position (the serving engine's
    slot pool, where slots hold requests of different lengths).  The
    per-row forms compose with Sq > 1: the serving engine's speculative
    verify scores L = spec_len + 1 continuation tokens per row in one
    forward, each row's causal mask anchored at its own ``q_offset`` and
    its ``kv_length`` frontier at ``q_offset + L`` (ring caches pass a
    ``kv_positions`` built from each row's write FRONTIER, which also
    hides the round's just-written future entries from its earlier
    queries).  The scalar form takes the exact code path it always did.  Together the two hooks
    carry the serving engine's BUCKETED PREFILL masking: at prefill time a
    batch of prompts right-padded to one bucket length needs only the
    causal mask — pad keys sit at positions >= every real query, so no
    real row's softmax ever sees them — and at decode time the per-row
    ``kv_length`` frontier keeps the padded cache tail masked until real
    writes overwrite it.  (Masking pad QUERIES' keys explicitly would be
    wrong under ``window``: a pad position past the real prompt can end up
    with an all-masked — empty — softmax row, and the resulting NaN
    output poisons real rows through the next layer's 0·NaN value
    products.  The causal mask always leaves a query its own key.)

    ``segment_ids`` (B, S) int: sequence-packing isolation — query and key
    attend only within equal segment ids (on top of causal/window), so
    several documents packed into one row never see each other.  Id 0 is
    the padding convention (``data/packing.py``); padded slots still see
    themselves under ``causal``, so no softmax row is ever empty.  With
    RoPE (relative positions) each packed document attends exactly as it
    would unpacked.  Self-attention only (Sq == Sk).
    """
    *_, d = q.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    b, sq, h, _ = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"num_heads {h} not divisible by kv heads {hkv}")
    window = validate_window(window, causal)
    if kv_positions is not None and not causal:
        raise ValueError("kv_positions (rolling-cache slot positions) "
                         "requires causal=True — its empty-slot masking "
                         "lives in the causal mask")
    if segment_ids is not None and k.shape[1] != sq:
        raise ValueError("segment_ids (sequence packing) is a "
                         "self-attention feature: Sq must equal Sk, got "
                         f"{sq} vs {k.shape[1]}")
    g = h // hkv
    qg = q.reshape(b, sq, hkv, g, d)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    k_pos = (jnp.arange(k.shape[1]) if kv_positions is None
             else jnp.asarray(kv_positions))
    per_row = (k_pos.ndim == 2
               or getattr(q_offset, "ndim", 0) >= 1
               or getattr(kv_length, "ndim", 0) >= 1)
    if causal:
        if per_row:
            # batched masks: row r is a request at its own position
            q_off = jnp.asarray(0 if q_offset is None else q_offset)
            q_pos = jnp.arange(sq)[None, :] + jnp.reshape(q_off, (-1, 1))
            kp = k_pos if k_pos.ndim == 2 else k_pos[None, :]  # (B|1, Sk)
            mask = kp[:, None, :] > q_pos[:, :, None]          # (B, Sq, Sk)
            if window is not None:
                mask = mask | (kp[:, None, :] <= q_pos[:, :, None] - window)
            if kv_positions is not None:
                mask = mask | (kp[:, None, :] < 0)  # negative = empty slot
            scores = jnp.where(mask[:, None, None], NEG_INF, scores)
        else:
            q_pos = jnp.arange(sq) + (0 if q_offset is None else q_offset)
            mask = k_pos[None, :] > q_pos[:, None]  # (Sq, Sk): True = hide
            if window is not None:
                mask = mask | (k_pos[None, :] <= q_pos[:, None] - window)
            if kv_positions is not None:
                mask = mask | (k_pos[None, :] < 0)  # negative = empty slot
            scores = jnp.where(mask[None, None, None], NEG_INF, scores)
    if kv_length is not None:
        if per_row:
            kl = jnp.reshape(jnp.asarray(kv_length), (-1, 1))  # (B, 1)
            kp = k_pos if k_pos.ndim == 2 else k_pos[None, :]
            scores = jnp.where((kp < kl)[:, None, None, None, :],
                               scores, NEG_INF)
        else:
            scores = jnp.where((k_pos < kv_length)[None, None, None, None],
                               scores, NEG_INF)
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        cross = seg[:, :, None] != seg[:, None, :]        # (B, Sq, Sk)
        scores = jnp.where(cross[:, None, None], NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, h, d)


def attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
              impl: Optional[str] = None, window: Optional[int] = None,
              segment_ids=None):
    """Dispatching entry point used by the MultiHeadAttention layer."""
    # validate before the window>=S normalization below, so the error
    # doesn't depend on the window size
    window = validate_window(window, causal)
    if window is not None and window >= k.shape[1]:
        window = None  # covers every key: mathematically plain causal
    if segment_ids is not None and impl == "pallas":
        # packing isolation is mask-level — the flash kernel has no
        # segment support, so packed batches take the XLA path
        raise ValueError("segment_ids (sequence packing) is not "
                         "supported by the pallas flash kernel — use "
                         "impl='xla' (or leave impl unset)")
    if impl is None:
        impl = ("pallas" if segment_ids is None and _pallas_eligible(q, k)
                else "xla")
    if impl == "xla":
        return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                     window=window, segment_ids=segment_ids)
    if impl == "pallas":
        from .flash_attention import flash_attention
        if k.shape[2] != q.shape[2]:
            # GQA/MQA: the kernel is written for equal head counts; repeat
            # k/v up to H.  The flash win (no S×S materialization) is
            # head-count independent, and the repeat is HBM-cheap next to
            # the scores it avoids; the GQA KV-cache/projection savings
            # live in the layer, not the kernel.
            if q.shape[2] % k.shape[2]:
                raise ValueError(f"num_heads {q.shape[2]} not divisible "
                                 f"by kv heads {k.shape[2]}")
            g = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               window=window)
    raise ValueError(f"unknown attention impl {impl!r}")


def _pallas_eligible(q, k) -> bool:
    """Fused kernel wants TPU, self-attention lengths (the kernel folds k/v
    with q's sequence length — cross-attention falls back to XLA), and a
    block-tileable sequence: a multiple of the 128-lane block, or a single
    block whose rows satisfy the strictest (bf16: 16) sublane tile.
    head_dim is unconstrained — the kernel's blocks span the whole (d) dim,
    which TPU tiling always allows (d=64 exercised by the hardware smoke
    test, tests/test_tpu_smoke.py)."""
    if jax.default_backend() != "tpu":
        return False
    if q.shape[1] != k.shape[1]:
        return False
    s = q.shape[1]
    return s % 128 == 0 or (s <= 128 and s % 16 == 0)
