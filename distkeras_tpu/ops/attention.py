"""Attention ops — XLA reference implementation + dispatch.

No counterpart exists in the reference (its models are MLPs/small ConvNets;
SURVEY.md §2.3 "sequence parallelism: absent") — this is part of the
framework's long-context layer.  Layout is **BSHD** ``(batch, seq, heads,
head_dim)`` throughout: S in the second dimension keeps the (S, Dh) matmuls
MXU-shaped and makes the sequence axis shardable for ring attention
(``parallel/ring.py``).

``impl``: ``"xla"`` — plain jnp, XLA fuses the softmax chain; ``"pallas"`` —
the fused flash kernel in ``ops/flash_attention.py`` (TPU); ``None`` — pick
pallas on TPU when shapes qualify, else xla.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def validate_window(window: Optional[int], causal: bool) -> Optional[int]:
    """The single sliding-window rule, shared by every attention entry
    point (XLA, flash, ring, layers): requires causal, must be >= 1."""
    if window is None:
        return None
    if not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True")
    if int(window) < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return int(window)


def dot_product_attention(q, k, v, *, causal: bool = False,
                          scale: Optional[float] = None,
                          q_offset=None, kv_length=None,
                          window: Optional[int] = None,
                          kv_positions=None, segment_ids=None,
                          q_positions=None):
    """Softmax(q·kᵀ)·v with f32 softmax arithmetic.

    q: (B, Sq, H, Dh); k, v: (B, Sk, Hkv, Dh) → (B, Sq, H, Dh), in q.dtype.
    Hkv may divide H (grouped-query / multi-query attention): each group of
    H/Hkv query heads shares one k/v head, shrinking the KV projection and —
    at decode time — the KV cache by the same factor.  Hkv == H is classic
    MHA; the grouped einsum below reduces to it at G == 1.

    ``window`` (requires ``causal``): sliding-window attention — query at
    position p sees keys in (p - window, p], i.e. itself and the previous
    ``window - 1`` tokens.  Information still propagates ``window`` tokens
    per layer, so reach grows with depth.  Here (the XLA path) the window
    is mask-only — scores are computed then hidden; the flash kernel
    (``flash_attention(window=...)``, used automatically on TPU) skips
    out-of-window blocks outright for true O(S·W) compute.

    KV-cache decoding hooks (``core/decode.py`` — keeps decode on this
    exact numerics path): ``q_offset`` places query i at absolute position
    ``q_offset + i`` for the causal mask (queries continuing a cached
    prefix); ``kv_length`` masks key slots >= it out of the softmax
    (zero-filled tail of a preallocated cache); ``kv_positions`` gives
    each key slot an EXPLICIT absolute position (rolling/ring-buffer
    caches, where slot order ≠ position order — negative = empty slot),
    overriding the identity slot→position layout that ``causal``/
    ``kv_length`` otherwise assume.  All accept tracers.

    Each hook also accepts a PER-ROW form — ``q_offset``/``kv_length`` of
    shape (B,), ``kv_positions`` of shape (B, Sk) — so one batched decode
    step can advance every row at its own position (the serving engine's
    slot pool, where slots hold requests of different lengths).  The
    per-row forms compose with Sq > 1: the serving engine's speculative
    verify scores L = spec_len + 1 continuation tokens per row in one
    forward, each row's causal mask anchored at its own ``q_offset`` and
    its ``kv_length`` frontier at ``q_offset + L`` (ring caches pass a
    ``kv_positions`` built from each row's write FRONTIER, which also
    hides the round's just-written future entries from its earlier
    queries).  The scalar form takes the exact code path it always did.  Together the two hooks
    carry the serving engine's BUCKETED PREFILL masking: at prefill time a
    batch of prompts right-padded to one bucket length needs only the
    causal mask — pad keys sit at positions >= every real query, so no
    real row's softmax ever sees them — and at decode time the per-row
    ``kv_length`` frontier keeps the padded cache tail masked until real
    writes overwrite it.  (Masking pad QUERIES' keys explicitly would be
    wrong under ``window``: a pad position past the real prompt can end up
    with an all-masked — empty — softmax row, and the resulting NaN
    output poisons real rows through the next layer's 0·NaN value
    products.  The causal mask always leaves a query its own key.)

    ``q_positions`` (B, Sq) int: EXPLICIT per-query absolute positions,
    overriding the ``q_offset + arange(Sq)`` layout (and forcing the
    per-row mask path).  The paged-KV suffix prefill uses it to clamp its
    right-pad queries onto the last real prompt position — a pad query
    past the view (or past a sliding window's reach over the view) would
    otherwise mask EVERY key and poison real rows with its empty-softmax
    NaN; clamped, it attends like the final real token and its junk
    output is simply discarded.  Real queries pass their true positions,
    so this is mask-identical to ``q_offset`` for them.

    ``segment_ids`` (B, S) int: sequence-packing isolation — query and key
    attend only within equal segment ids (on top of causal/window), so
    several documents packed into one row never see each other.  Id 0 is
    the padding convention (``data/packing.py``); padded slots still see
    themselves under ``causal``, so no softmax row is ever empty.  With
    RoPE (relative positions) each packed document attends exactly as it
    would unpacked.  Self-attention only (Sq == Sk).
    """
    *_, d = q.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    b, sq, h, _ = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"num_heads {h} not divisible by kv heads {hkv}")
    window = validate_window(window, causal)
    if kv_positions is not None and not causal:
        raise ValueError("kv_positions (rolling-cache slot positions) "
                         "requires causal=True — its empty-slot masking "
                         "lives in the causal mask")
    if segment_ids is not None and k.shape[1] != sq:
        raise ValueError("segment_ids (sequence packing) is a "
                         "self-attention feature: Sq must equal Sk, got "
                         f"{sq} vs {k.shape[1]}")
    g = h // hkv
    qg = q.reshape(b, sq, hkv, g, d)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k,
                        preferred_element_type=jnp.float32) * scale
    k_pos = (jnp.arange(k.shape[1]) if kv_positions is None
             else jnp.asarray(kv_positions))
    per_row = (q_positions is not None
               or k_pos.ndim == 2
               or getattr(q_offset, "ndim", 0) >= 1
               or getattr(kv_length, "ndim", 0) >= 1)
    if causal:
        if per_row:
            # batched masks: row r is a request at its own position
            if q_positions is not None:
                q_pos = jnp.asarray(q_positions)
            else:
                q_off = jnp.asarray(0 if q_offset is None else q_offset)
                q_pos = jnp.arange(sq)[None, :] + jnp.reshape(q_off,
                                                              (-1, 1))
            kp = k_pos if k_pos.ndim == 2 else k_pos[None, :]  # (B|1, Sk)
            mask = kp[:, None, :] > q_pos[:, :, None]          # (B, Sq, Sk)
            if window is not None:
                mask = mask | (kp[:, None, :] <= q_pos[:, :, None] - window)
            if kv_positions is not None:
                mask = mask | (kp[:, None, :] < 0)  # negative = empty slot
            scores = jnp.where(mask[:, None, None], NEG_INF, scores)
        else:
            q_pos = jnp.arange(sq) + (0 if q_offset is None else q_offset)
            mask = k_pos[None, :] > q_pos[:, None]  # (Sq, Sk): True = hide
            if window is not None:
                mask = mask | (k_pos[None, :] <= q_pos[:, None] - window)
            if kv_positions is not None:
                mask = mask | (k_pos[None, :] < 0)  # negative = empty slot
            scores = jnp.where(mask[None, None, None], NEG_INF, scores)
    if kv_length is not None:
        if per_row:
            kl = jnp.reshape(jnp.asarray(kv_length), (-1, 1))  # (B, 1)
            kp = k_pos if k_pos.ndim == 2 else k_pos[None, :]
            scores = jnp.where((kp < kl)[:, None, None, None, :],
                               scores, NEG_INF)
        else:
            scores = jnp.where((k_pos < kv_length)[None, None, None, None],
                               scores, NEG_INF)
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        cross = seg[:, :, None] != seg[:, None, :]        # (B, Sq, Sk)
        scores = jnp.where(cross[:, None, None], NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v)
    return out.reshape(b, sq, h, d)


# ---------------------------------------------------------------------------
# paged-KV (block-table) forms — the serving engine's paged slot pool
# ---------------------------------------------------------------------------

def paged_gather(arena, block_tables, page_size: int, view_len: int):
    """Gather a per-row logical K/V view out of a flat paged arena.

    ``arena``: (A, ...) — a flat pool of fixed-size blocks laid out
    contiguously along axis 0 (``A = (num_blocks + 1) * page_size``; the
    trailing block is the NULL block junk writes are routed into).
    ``block_tables``: (B, T) int32 — row r's logical block i lives at
    physical block ``block_tables[r, i]``; entries equal to the null
    block id drop reads into junk (masked by the caller's frontier).
    Returns the (B, view_len, ...) logical view: entry (r, p) is the
    arena slot holding row r's logical position p.  This is the
    gather-by-block-table read the paged decode/prefill programs run —
    the values are bit-identical to a dense (B, view_len, ...) cache
    holding the same writes, so attention over the view reproduces the
    dense path's numerics exactly.
    """
    idx = jnp.arange(int(view_len))
    blk = jnp.minimum(idx // int(page_size), block_tables.shape[1] - 1)
    phys = (jnp.take(block_tables, blk, axis=1) * int(page_size)
            + (idx % int(page_size))[None, :])            # (B, view_len)
    return arena[phys]


def paged_attention(q, k_arena, v_arena, block_tables, page_size: int,
                    view_len: int, *, q_positions=None, q_offset=None,
                    kv_length=None, window: Optional[int] = None,
                    kv_positions=None, scale: Optional[float] = None):
    """``dot_product_attention`` over block-table-gathered K/V: each row's
    keys/values are gathered from the flat ``k_arena``/``v_arena`` through
    its block table, then attended with the usual per-row causal masks
    (``q_positions``/``q_offset`` anchor the queries, ``kv_length`` masks
    the unwritten logical tail, ``kv_positions`` carries ring layouts).
    Quantized arenas dequantize BEFORE this entry point (the caller
    gathers codes + scales and fuses the dequant — see
    ``core/decode.py``)."""
    k = paged_gather(k_arena, block_tables, page_size, view_len)
    v = paged_gather(v_arena, block_tables, page_size, view_len)
    return dot_product_attention(q, k, v, causal=True, scale=scale,
                                 q_positions=q_positions, q_offset=q_offset,
                                 kv_length=kv_length, window=window,
                                 kv_positions=kv_positions)


def attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
              impl: Optional[str] = None, window: Optional[int] = None,
              segment_ids=None):
    """Dispatching entry point used by the MultiHeadAttention layer."""
    # validate before the window>=S normalization below, so the error
    # doesn't depend on the window size
    window = validate_window(window, causal)
    if window is not None and window >= k.shape[1]:
        window = None  # covers every key: mathematically plain causal
    if segment_ids is not None and impl == "pallas":
        # packing isolation is mask-level — the flash kernel has no
        # segment support, so packed batches take the XLA path
        raise ValueError("segment_ids (sequence packing) is not "
                         "supported by the pallas flash kernel — use "
                         "impl='xla' (or leave impl unset)")
    if impl is None:
        impl = ("pallas" if segment_ids is None and _pallas_eligible(q, k)
                else "xla")
    if impl == "xla":
        return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                     window=window, segment_ids=segment_ids)
    if impl == "pallas":
        from .flash_attention import flash_attention
        if k.shape[2] != q.shape[2]:
            # GQA/MQA: the kernel is written for equal head counts; repeat
            # k/v up to H.  The flash win (no S×S materialization) is
            # head-count independent, and the repeat is HBM-cheap next to
            # the scores it avoids; the GQA KV-cache/projection savings
            # live in the layer, not the kernel.
            if q.shape[2] % k.shape[2]:
                raise ValueError(f"num_heads {q.shape[2]} not divisible "
                                 f"by kv heads {k.shape[2]}")
            g = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, g, axis=2)
            v = jnp.repeat(v, g, axis=2)
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               window=window)
    raise ValueError(f"unknown attention impl {impl!r}")


def _pallas_eligible(q, k) -> bool:
    """Fused kernel wants TPU, self-attention lengths (the kernel folds k/v
    with q's sequence length — cross-attention falls back to XLA), and a
    block-tileable sequence: a multiple of the 128-lane block, or a single
    block whose rows satisfy the strictest (bf16: 16) sublane tile.
    head_dim is unconstrained — the kernel's blocks span the whole (d) dim,
    which TPU tiling always allows (d=64 exercised by the hardware smoke
    test, tests/test_tpu_smoke.py)."""
    if jax.default_backend() != "tpu":
        return False
    if q.shape[1] != k.shape[1]:
        return False
    s = q.shape[1]
    return s % 128 == 0 or (s <= 128 and s % 16 == 0)
