"""Attention ops — XLA reference implementation + dispatch.

No counterpart exists in the reference (its models are MLPs/small ConvNets;
SURVEY.md §2.3 "sequence parallelism: absent") — this is part of the
framework's long-context layer.  Layout is **BSHD** ``(batch, seq, heads,
head_dim)`` throughout: S in the second dimension keeps the (S, Dh) matmuls
MXU-shaped and makes the sequence axis shardable for ring attention
(``parallel/ring.py``).

``impl``: ``"xla"`` — plain jnp, XLA fuses the softmax chain; ``"pallas"`` —
the fused flash kernel in ``ops/flash_attention.py`` (TPU); ``None`` — pick
pallas on TPU when shapes qualify, else xla.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = float("-inf")


def dot_product_attention(q, k, v, *, causal: bool = False,
                          scale: Optional[float] = None):
    """Softmax(q·kᵀ)·v with f32 softmax arithmetic.

    q: (B, Sq, H, Dh); k, v: (B, Sk, H, Dh) → (B, Sq, H, Dh), in q.dtype.
    """
    *_, d = q.shape
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])
        k_pos = jnp.arange(k.shape[1])
        mask = k_pos[None, :] > q_pos[:, None]  # (Sq, Sk): True = hide
        scores = jnp.where(mask[None, None], NEG_INF, scores)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
              impl: Optional[str] = None):
    """Dispatching entry point used by the MultiHeadAttention layer."""
    if impl is None:
        impl = "pallas" if _pallas_eligible(q) else "xla"
    if impl == "xla":
        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    if impl == "pallas":
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")


def _pallas_eligible(q) -> bool:
    """Fused kernel wants TPU + lane-aligned head_dim + tileable seq."""
    if jax.default_backend() != "tpu":
        return False
    b, s, h, d = q.shape
    return d % 128 == 0 and s % 128 == 0
