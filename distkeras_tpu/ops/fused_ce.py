"""Fused softmax-cross-entropy in Pallas (TPU) — the LM-head hot op.

The last op of every LM train step is ``-log_softmax(logits)[label]`` over a
(tokens, vocab) logits matrix.  XLA's lowering materializes the full f32
log-probability matrix in HBM (at vocab 50k and 8k tokens that is a 1.6 GB
round-trip per step — comparable to the whole rest of the backward).  This
kernel computes the per-token loss in ONE streaming pass with the
online-softmax recurrence, so HBM traffic is read-logits-once plus an O(T)
write, and nothing (T, V)-shaped is ever written:

 - forward, grid (T/block_t, V/block_v): the inner grid dimension streams
   vocab blocks through VMEM; f32 scratch carries the running max / sum-exp
   / picked-label-logit across inner iterations (TPU grids run sequentially,
   innermost fastest); the last block writes per-row ``loss = lse - picked``
   and the ``lse`` residual, both broadcast over a 128-lane trailing dim
   (the TPU-tileable layout for per-row stats, as in flash_attention);
 - backward, grid (T/block_t, V/block_v): pure streaming map — each block
   recomputes ``p = exp(logits - lse)`` from the saved O(T) residual and
   writes ``ct · (p - onehot(label))``; no scratch carry, no (T, V)
   intermediate beyond the unavoidable gradient output itself (written in
   the logits dtype, not f32);
 - ragged edges are handled in-kernel: vocab/token positions past the true
   extent are masked to -inf / zero contribution, so any (T, V) shape works
   without host-side padding copies.

On non-TPU backends the kernel runs in Pallas interpret mode (tests); the
XLA path (``core.losses.sparse_categorical_crossentropy`` on log_softmax)
stays the correctness oracle — value/grad parity asserted in
tests/test_fused_ce.py.  No reference counterpart (the reference's losses
are whole-array Keras ops; SURVEY.md §2.1 row 21) — this exists because a
TPU-first LM stack is HBM-bound exactly here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._vma import _vma_of, out_struct

NEG_INF = float("-inf")
_LANES = 128


def _col_ids(v0, bt, bv):
    return v0 + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)


def _fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref,
                m_scr, l_scr, pick_scr, *,
                block_t: int, block_v: int, num_v: int, v_total: int):
    vj = pl.program_id(1)
    bt, bv = block_t, block_v

    @pl.when(vj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        pick_scr[...] = jnp.zeros_like(pick_scr)

    s = logits_ref[...].astype(jnp.float32)                 # (bt, bv)
    cols = _col_ids(vj * bv, bt, bv)
    s = jnp.where(cols < v_total, s, NEG_INF)               # ragged vocab edge

    lab = labels_ref[...]                                   # (bt, 1) int32
    hit = (cols == lab)                                     # one-hot block
    # the label column appears in exactly one vocab block, so += is a select
    pick_scr[...] = pick_scr[...] + jnp.broadcast_to(
        jnp.sum(jnp.where(hit, s, 0.0), axis=-1, keepdims=True),
        pick_scr.shape)

    m = m_scr[:, 0:1]
    l = l_scr[:, 0:1]
    new_m = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    safe = jnp.where(new_m == NEG_INF, 0.0, new_m)
    p = jnp.exp(s - safe)                                   # -inf cols -> 0
    l = l * jnp.exp(m - safe) + jnp.sum(p, axis=-1, keepdims=True)
    m_scr[...] = jnp.broadcast_to(new_m, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l, l_scr.shape)

    @pl.when(vj == num_v - 1)
    def _finalize():
        m = m_scr[:, 0:1]
        l = l_scr[:, 0:1]
        safe_m = jnp.where(m == NEG_INF, 0.0, m)
        lse = safe_m + jnp.log(jnp.where(l == 0.0, 1.0, l))
        loss_ref[...] = jnp.broadcast_to(lse - pick_scr[:, 0:1],
                                         loss_ref.shape)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def _bwd_kernel(logits_ref, labels_ref, lse_ref, ct_ref, dlogits_ref, *,
                block_t: int, block_v: int, v_total: int):
    vj = pl.program_id(1)
    bt, bv = block_t, block_v
    s = logits_ref[...].astype(jnp.float32)
    cols = _col_ids(vj * bv, bt, bv)
    lse = lse_ref[:, 0:1]
    p = jnp.where(cols < v_total, jnp.exp(s - lse), 0.0)
    hit = (cols == labels_ref[...]).astype(jnp.float32)
    ct = ct_ref[:, 0:1]
    # ragged token rows need no masking here: writes to out-of-range rows
    # of an edge block are dropped by pallas, and every op is row-local
    dlogits_ref[...] = (ct * (p - hit)).astype(dlogits_ref.dtype)


def _resolve_interpret(interpret):
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _specs(bt, bv):
    return dict(
        logits=pl.BlockSpec((bt, bv), lambda ti, vj: (ti, vj)),
        rows=pl.BlockSpec((bt, 1), lambda ti, vj: (ti, 0)),
        lanes=pl.BlockSpec((bt, _LANES), lambda ti, vj: (ti, 0)),
    )


def _fwd_call(logits, labels, block_t, block_v, interpret):
    t, v = logits.shape
    bt = min(block_t, t)
    bv = min(block_v, v)
    grid = (pl.cdiv(t, bt), pl.cdiv(v, bv))
    sp = _specs(bt, bv)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, block_t=bt, block_v=bv,
                          num_v=grid[1], v_total=v),
        out_shape=(out_struct((t, _LANES), jnp.float32, logits),
                   out_struct((t, _LANES), jnp.float32, logits)),
        grid=grid,
        in_specs=[sp["logits"], sp["rows"]],
        out_specs=(sp["lanes"], sp["lanes"]),
        scratch_shapes=[pltpu.VMEM((bt, _LANES), jnp.float32),
                        pltpu.VMEM((bt, _LANES), jnp.float32),
                        pltpu.VMEM((bt, _LANES), jnp.float32)],
        interpret=interpret,
    )(logits, labels.reshape(t, 1).astype(jnp.int32))
    return loss[:, 0], lse[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _fused_ce(logits, labels, block_t: int, block_v: int, interpret: bool):
    loss, _ = _fwd_call(logits, labels, block_t, block_v, interpret)
    return loss


def fused_softmax_cross_entropy(logits, labels, block_t: int = 256,
                                block_v: int = 512,
                                interpret: Optional[bool] = None):
    """Per-token ``-log_softmax(logits)[label]`` without materializing the
    (T, V) log-probability matrix.

    logits: (T, V) any float dtype; labels: (T,) integer class ids.
    Returns (T,) f32 losses — sum/mean (and psum, under shard_map) are the
    caller's.  Differentiable wrt ``logits`` (grad streams block-wise from
    an O(T) logsumexp residual, written in the logits dtype).

    Under shard_map on a non-TPU backend the call falls back to the XLA
    math: interpret-mode kernels inline into the traced program, where the
    scratch-carried online recurrence cannot satisfy shard_map's
    varying-axes checks (compiled TPU kernels trace in a fresh context and
    are unaffected — same dispatch rule as ``ops.attention``).
    """
    interpret = _resolve_interpret(interpret)
    if interpret and _vma_of(logits):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return _fused_ce(logits, labels, block_t, block_v, interpret)


def _ce_fwd(logits, labels, block_t, block_v, interpret):
    loss, lse = _fwd_call(logits, labels, block_t, block_v, interpret)
    return loss, (logits, labels, lse)


def _ce_bwd(block_t, block_v, interpret, res, g):
    logits, labels, lse = res
    t, v = logits.shape
    bt = min(block_t, t)
    bv = min(block_v, v)
    sp = _specs(bt, bv)
    # per-row cotangent and lse ride the lane-broadcast layout
    ct = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (t, _LANES))
    lse_b = jnp.broadcast_to(lse[:, None], (t, _LANES))
    dlogits = pl.pallas_call(
        functools.partial(_bwd_kernel, block_t=bt, block_v=bv, v_total=v),
        out_shape=out_struct((t, v), logits.dtype, logits),
        grid=(pl.cdiv(t, bt), pl.cdiv(v, bv)),
        in_specs=[sp["logits"], sp["rows"], sp["lanes"], sp["lanes"]],
        out_specs=sp["logits"],
        interpret=interpret,
    )(logits, labels.reshape(t, 1).astype(jnp.int32), lse_b, ct)
    return dlogits, None


_fused_ce.defvjp(_ce_fwd, _ce_bwd)
